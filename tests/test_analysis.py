"""The invariant analyzer suite (go_crdt_playground_tpu/analysis/).

Two halves, mirroring DESIGN.md §15's contract:

* **the gate is green** on the current tree — the tier-1 hook
  (``test_gate_fast``) runs the full ``--fast`` gate exactly as CI does
  and demands zero errors, all passes covered;
* **every pass can fail** — each analyzer gets a planted violation
  (guarded-by breach, lock-order cycle, requires-lock bypass, missing
  fsync, impure jit function, unlocked shared write, broken join) and
  must detect it.  A gate that cannot fail proves nothing.

Also here: regression tests for the true positives the passes flagged
on the pre-analyzer tree (the ``_conn_slots`` handoff leak, the
resync-epoch fields mutated without the node lock).
"""

import socket
import threading
import time

import numpy as np
import pytest

from go_crdt_playground_tpu.analysis import durability, lockdiscipline, purity
from go_crdt_playground_tpu.analysis.annotations import parse_annotations
from go_crdt_playground_tpu.analysis.locksets import RaceDetector
from go_crdt_playground_tpu.analysis.report import Report
from go_crdt_playground_tpu.utils.guards import AlreadyInstalledError


# ---------------------------------------------------------------------------
# annotation grammar
# ---------------------------------------------------------------------------


def test_annotation_parse_inline_and_standalone():
    src = (
        "class C:\n"
        "    def __init__(self):\n"
        "        self.x = 1  # guarded-by: _lock\n"
        "        # race-ok: owner thread only\n"
        "        self.y = 2\n"
    )
    a = parse_annotations(src)
    assert a.on_lines(3, 3).kind == "guarded-by"
    assert a.on_lines(3, 3).arg == "_lock"
    # the standalone comment on line 4 attaches to line 5
    assert a.on_lines(5, 5).kind == "race-ok"
    assert not a.malformed


def test_annotation_missing_arg_is_malformed_not_silent():
    a = parse_annotations("x = 1  # guarded-by:\n")
    assert a.malformed, "a typo'd contract must be surfaced, not skipped"


def test_standalone_annotation_skips_continuation_comments():
    """Review regression: an annotation whose reason wraps onto further
    comment lines must attach to the statement below the block, not to
    its own continuation comment."""
    src = (
        "class C:\n"
        "    def __init__(self):\n"
        "        # race-ok: a reason long enough that the author\n"
        "        # wrapped it onto a second comment line\n"
        "        self.y = 2\n"
    )
    a = parse_annotations(src)
    got = a.on_lines(5, 5)
    assert got is not None and got.kind == "race-ok", a.by_line


def test_annotations_attach_to_annassign_statements():
    """Review regression: ``self.x: T = v  # guarded-by: L`` is an
    ast.AnnAssign — both the static lint and the runtime detector must
    honor annotations on type-annotated assignments."""
    findings = _lint_source(
        "import threading\n"
        "from typing import Optional\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.x: Optional[int] = None  # guarded-by: _lock\n"
        "    def bad(self):\n"
        "        self.x = 5\n"
    )
    assert [f for f in findings if f.code == "L001" and f.line == 8], \
        findings


def test_race_ok_on_annassign_excludes_field_at_runtime():
    from typing import Optional

    from go_crdt_playground_tpu.analysis.locksets import _race_ok_fields

    class AnnAnnotated:
        def __init__(self):
            self._lock = threading.Lock()
            self.flag: Optional[int] = None  # race-ok: planted for test

    assert "flag" in _race_ok_fields(AnnAnnotated)


def test_node_lifecycle_fields_stay_silent_under_detector():
    """Review regression (the phantom-race repro): instrument a Node,
    serve, take one dial, close — the race-ok'd owner-thread lifecycle
    fields (_server_sock/_server_thread/_closing, all AnnAssign or
    wrapped-comment annotated) must not be reported."""
    from go_crdt_playground_tpu.net.peer import Node

    det = RaceDetector()
    node = det.instrument(Node(0, 8, 2))
    host, port = node.serve()
    s = socket.create_connection((host, port), timeout=2.0)
    time.sleep(0.2)
    s.close()
    node.close()
    assert not det.findings, [f.render() for f in det.findings]


# ---------------------------------------------------------------------------
# pass 1: lock-discipline lint (planted violations)
# ---------------------------------------------------------------------------


def _lint_source(src, **kw):
    lint = lockdiscipline.LockLint(**kw)
    lint.load_file("<planted>", source=src)
    return lint.run()


def test_guarded_by_violation_detected():
    findings = _lint_source(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.x = 0  # guarded-by: _lock\n"
        "    def good(self):\n"
        "        with self._lock:\n"
        "            self.x += 1\n"
        "    def bad(self):\n"
        "        self.x += 1\n"
    )
    assert [f for f in findings if f.code == "L001"
            and f.symbol == "C.x" and f.line == 10], findings
    assert not [f for f in findings if f.line == 8], \
        "the locked access must not be flagged"


def test_foreign_name_store_checked():
    findings = _lint_source(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.x = 0  # guarded-by: _lock\n"
        "    @classmethod\n"
        "    def make(cls):\n"
        "        c = C()\n"
        "        c.x = 5\n"
        "        return c\n"
    )
    assert [f for f in findings if f.code == "L001" and f.line == 9], \
        "alternate-constructor writes through other names must be checked"


def test_requires_lock_call_site_checked():
    findings = _lint_source(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.x = 0  # guarded-by: _lock\n"
        "    # requires-lock: _lock\n"
        "    def _mutate(self):\n"
        "        self.x += 1\n"
        "    def good(self):\n"
        "        with self._lock:\n"
        "            self._mutate()\n"
        "    def bad(self):\n"
        "        self._mutate()\n"
    )
    bad = [f for f in findings if f.code == "L001"]
    assert len(bad) == 1 and bad[0].line == 13, findings


def test_lock_order_cycle_detected():
    findings = _lint_source(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n"
        "    def one(self):\n"
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n"
        "    def two(self):\n"
        "        with self._b:\n"
        "            with self._a:\n"
        "                pass\n"
    )
    assert [f for f in findings if f.code == "L002"], \
        "opposite-order acquisition must be rejected as a deadlock risk"


def test_inconsistently_locked_mutable_field_flagged():
    findings = _lint_source(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.n = 0\n"
        "    def locked_bump(self):\n"
        "        with self._lock:\n"
        "            self.n += 1\n"
        "    def bare_bump(self):\n"
        "        self.n += 1\n"
    )
    assert [f for f in findings if f.code == "L003"
            and f.symbol == "C.n"], findings


def test_immutable_config_reads_not_flagged():
    findings = _lint_source(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.cfg = 7\n"
        "        self.n = 0  # guarded-by: _lock\n"
        "    def locked(self):\n"
        "        with self._lock:\n"
        "            self.n = self.cfg\n"
        "    def bare(self):\n"
        "        return self.cfg\n"
    )
    assert not findings, "set-once config fields cannot race"


# ---------------------------------------------------------------------------
# pass 2: runtime lockset race detector (planted races)
# ---------------------------------------------------------------------------


class _Shared:
    def __init__(self):
        self._lock = threading.Lock()
        self.racy = 0
        self.safe = 0
        self.bag = set()   # mutated through reads (container tracking)


def _hammer(fn, n_threads=2, iters=300):
    errs = []

    def run():
        try:
            for _ in range(iters):
                fn()
        except BaseException as e:  # pragma: no cover - debug aid
            errs.append(e)

    ts = [threading.Thread(target=run) for _ in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs, errs


def test_unlocked_shared_write_reported():
    det = RaceDetector()
    obj = det.instrument(_Shared())

    def work():
        obj.racy += 1            # no lock: the planted race
        with obj._lock:
            obj.safe += 1        # locked: must stay silent

    _hammer(work)
    symbols = {f.symbol for f in det.findings}
    assert "_Shared.racy" in symbols, det.stats()
    assert "_Shared.safe" not in symbols, \
        "a consistently locked field must not be reported"


def test_container_mutation_via_read_reported():
    det = RaceDetector()
    obj = det.instrument(_Shared())
    _hammer(lambda: obj.bag.add(1))
    assert any(f.symbol == "_Shared.bag" for f in det.findings), \
        ".add() mutates through an attribute READ; reads of mutable " \
        "containers must count as writes"


def test_sequential_threads_still_detected():
    """pthread-id reuse regression: a thread that finishes before the
    next starts must still count as a distinct thread."""
    det = RaceDetector()
    obj = det.instrument(_Shared())
    for _ in range(2):
        t = threading.Thread(target=lambda: [obj.__setattr__(
            "racy", obj.racy + 1) for _ in range(10)])
        t.start()
        t.join()   # fully sequential: idents may be recycled
    assert any(f.symbol == "_Shared.racy" for f in det.findings)


def test_single_thread_never_reported():
    det = RaceDetector()
    obj = det.instrument(_Shared())
    for _ in range(100):
        obj.racy += 1
        obj.bag.add(1)
    assert not det.findings, "the exclusive warm-up state must be free"


def test_double_install_raises_cleanly():
    det = RaceDetector()
    obj = det.instrument(_Shared())
    with pytest.raises(AlreadyInstalledError):
        det.instrument(obj)
    with pytest.raises(AlreadyInstalledError):
        RaceDetector().instrument(obj)   # a second detector counts too
    det.uninstall(obj)
    det.instrument(obj)   # after uninstall, reinstall is legal
    det.uninstall(obj)


def test_uninstall_restores_class_and_locks():
    det = RaceDetector()
    obj = det.instrument(_Shared())
    assert type(obj).__name__ == "Traced_Shared"
    det.uninstall(obj)
    assert type(obj) is _Shared
    assert isinstance(obj._lock, type(threading.Lock()))


def test_unbalanced_uninstall_refuses_without_corrupting():
    """Review regression: uninstall of a never-instrumented object must
    raise BEFORE touching the object's class."""
    det = RaceDetector()
    obj = _Shared()
    with pytest.raises(KeyError):
        det.uninstall(obj)
    assert type(obj) is _Shared, "a refused uninstall must not demote " \
                                 "the object's class"


def test_dropped_detector_releases_shim_key():
    """Review regression: a detector garbage-collected without
    uninstall() must not pin the shim key (recycled id() values would
    make a later instrument() spuriously refuse)."""
    import gc

    from go_crdt_playground_tpu.utils.guards import SHIM_GUARD

    det = RaceDetector()
    obj = det.instrument(_Shared())
    key = ("race-detector", id(obj))
    assert SHIM_GUARD.installed(key)
    del det, obj
    gc.collect()
    assert not SHIM_GUARD.installed(key), \
        "finalizer must return the key when the object dies uninstalled"


def test_property_results_are_not_traced():
    """Review regression: a lock-correct property returning a fresh
    container resolves at class level AFTER its getter released the
    lock; tracing its result would fabricate an unlocked shared write."""

    class WithProp:
        def __init__(self):
            self._lock = threading.Lock()
            self._items: list = []  # guarded-by: _lock

        @property
        def items(self):
            with self._lock:
                return list(self._items)

    det = RaceDetector()
    obj = det.instrument(WithProp())
    _hammer(lambda: obj.items)
    assert not det.findings, [f.render() for f in det.findings]


def test_standalone_annotation_skips_blank_line():
    src = (
        "class C:\n"
        "    def __init__(self):\n"
        "        # race-ok: reason\n"
        "\n"
        "        self.y = 2\n"
    )
    a = parse_annotations(src)
    got = a.on_lines(5, 5)
    assert got is not None and got.kind == "race-ok", a.by_line


def test_same_named_guarded_fields_do_not_collide():
    """Review regression: two classes guarding a same-named field must
    each keep their own contract in the cross-file registry."""
    lint = lockdiscipline.LockLint(attr_classes={"n": "Node"})
    lint.load_file("<a>", source=(
        "import threading\n"
        "class Node:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._state = 0  # guarded-by: _lock\n"
        "    @classmethod\n"
        "    def restore(cls):\n"
        "        n = cls()\n"
        "        n._state = 1\n"
        "        return n\n"
    ))
    lint.load_file("<b>", source=(
        "import threading\n"
        "class Breaker:\n"
        "    def __init__(self):\n"
        "        self._mu = threading.Lock()\n"
        "        self._state = 'closed'  # guarded-by: _mu\n"
        "    def ok(self):\n"
        "        with self._mu:\n"
        "            self._state = 'open'\n"
    ))
    findings = lint.run()
    bad = [f for f in findings if f.code == "L001"]
    assert len(bad) == 1 and bad[0].symbol == "Node._state", findings
    assert "n._lock" in bad[0].message, \
        "the hinted owner's lock, not the other class's, must be named"


def test_race_ok_annotation_excludes_field():
    class Annotated:
        def __init__(self):
            self._lock = threading.Lock()
            self.noisy = 0  # race-ok: planted benign flag for the test

    det = RaceDetector()
    obj = det.instrument(Annotated())
    _hammer(lambda: setattr(obj, "noisy", obj.noisy + 1))
    assert not det.findings, \
        "race-ok fields are excluded from the state machine"


# ---------------------------------------------------------------------------
# pass 3a: durability-ordering lint (planted missing fsync)
# ---------------------------------------------------------------------------


def test_unfsynced_rename_detected():
    findings, _ = durability.analyze_file("<planted>", source=(
        "import os\n"
        "def publish(tmp, path):\n"
        "    with open(tmp, 'w') as f:\n"
        "        f.write('data')\n"
        "    os.replace(tmp, path)\n"
    ))
    assert [f for f in findings if f.code == "D001" and f.line == 5]


def test_fsynced_rename_clean():
    findings, _ = durability.analyze_file("<planted>", source=(
        "import os\n"
        "def publish(tmp, path):\n"
        "    with open(tmp, 'w') as f:\n"
        "        f.write('data')\n"
        "        f.flush()\n"
        "        os.fsync(f.fileno())\n"
        "    os.replace(tmp, path)\n"
    ))
    assert not findings


def test_durable_on_return_without_fsync_detected():
    findings, _ = durability.analyze_file("<planted>", source=(
        "class W:\n"
        "    # durable-on-return\n"
        "    def append(self, b):\n"
        "        self.f.write(b)\n"
        "        self.f.flush()\n"
    ))
    assert [f for f in findings if f.code == "D001"
            and f.symbol == "W.append"]


def test_helper_fsync_credited():
    findings, stats = durability.analyze_file("<planted>", source=(
        "import os\n"
        "def flush_dir(p):\n"
        "    fd = os.open(p, os.O_RDONLY)\n"
        "    os.fsync(fd)\n"
        "    os.close(fd)\n"
        "def publish(tmp, path):\n"
        "    flush_dir(tmp)\n"
        "    os.replace(tmp, path)\n"
    ))
    assert not findings
    assert "flush_dir" in stats["local_fsyncers"]


# ---------------------------------------------------------------------------
# pass 3b: JAX-purity lint (planted impurities)
# ---------------------------------------------------------------------------


def test_impure_jit_function_detected():
    findings, stats = purity.analyze_file("<planted>", source=(
        "import time\n"
        "import jax\n"
        "@jax.jit\n"
        "def bad(x):\n"
        "    t = time.time()\n"
        "    return x + t\n"
    ))
    assert [f for f in findings if f.code == "P001" and f.line == 5]
    assert "bad" in stats["jit_roots"]


def test_impurity_in_reachable_helper_detected():
    findings, _ = purity.analyze_file("<planted>", source=(
        "import jax\n"
        "def helper(x):\n"
        "    print(x)\n"
        "    return x\n"
        "@jax.jit\n"
        "def root(x):\n"
        "    return helper(x)\n"
    ))
    assert [f for f in findings if f.code == "P001" and f.symbol == "helper"]


def test_pallas_caller_is_a_root():
    findings, stats = purity.analyze_file("<planted>", source=(
        "import random\n"
        "from jax.experimental import pallas as pl\n"
        "def kernel_host(x):\n"
        "    r = random.random()\n"
        "    return pl.pallas_call(lambda ref: ref, out_shape=x)(x + r)\n"
    ))
    assert "kernel_host" in stats["jit_roots"]
    assert [f for f in findings if f.code == "P001"]


def test_unreachable_impurity_not_flagged():
    findings, _ = purity.analyze_file("<planted>", source=(
        "import time, jax\n"
        "def host_only():\n"
        "    return time.time()\n"
        "@jax.jit\n"
        "def pure(x):\n"
        "    return x * 2\n"
    ))
    assert not findings, "host-side helpers outside the traced graph " \
                         "are legal"


# ---------------------------------------------------------------------------
# pass 4: lattice-law checker (planted broken joins)
# ---------------------------------------------------------------------------


def _broken_spec(join_fn, name):
    from go_crdt_playground_tpu.ops import lattices

    base = lattices.JOIN_REGISTRY["gcounter"]
    return lattices.JoinSpec(name, base.sample, join_fn, base.project)


def test_noncommutative_join_detected():
    from go_crdt_playground_tpu.analysis import lattice_laws

    def left_biased(dst, src):
        return dst  # "merge" that ignores src entirely

    findings, _ = lattice_laws.check_join_spec(
        _broken_spec(left_biased, "left_biased"), seeds=(3,))
    assert findings and findings[0].code in ("J001", "J002"), findings


def test_nonidempotent_join_detected():
    from go_crdt_playground_tpu.analysis import lattice_laws

    def summing(dst, src):
        return dst._replace(counts=dst.counts + src.counts)

    findings, _ = lattice_laws.check_join_spec(
        _broken_spec(summing, "summing"), seeds=(3,))
    assert any(f.code == "J003" for f in findings) or findings, \
        "element-sum is not idempotent and must be rejected"


def test_registry_covers_all_families():
    from go_crdt_playground_tpu.ops import lattices
    from go_crdt_playground_tpu.ops import merge  # noqa: F401

    assert {"gcounter", "pncounter", "twopset", "lwwmap", "mvregister",
            "ormap", "awset_merge"} <= set(lattices.JOIN_REGISTRY)


# ---------------------------------------------------------------------------
# regression tests for the true positives the passes flagged (satellite 1)
# ---------------------------------------------------------------------------


def test_conn_slot_released_when_handler_spawn_fails(monkeypatch):
    """Pre-fix, a non-RuntimeError failure between slot acquire and
    thread start leaked the slot forever (capacity decay)."""
    from go_crdt_playground_tpu.net import peer as peer_mod

    node = peer_mod.Node(0, 8, 2, max_conns=1)
    host, port = node.serve()
    try:
        real_thread = threading.Thread
        calls = {"n": 0}

        class ExplodingThread:
            def __init__(self, *a, **kw):
                calls["n"] += 1
                raise RuntimeError("planted thread exhaustion")

        monkeypatch.setattr(peer_mod.threading, "Thread", ExplodingThread)
        s = socket.create_connection((host, port), timeout=2.0)
        deadline = time.monotonic() + 5.0
        while calls["n"] == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        s.close()
        assert calls["n"] > 0, "accept loop never tried to spawn"
        monkeypatch.setattr(peer_mod.threading, "Thread", real_thread)
        # the ONLY slot must have been returned: a real exchange works
        peer = peer_mod.Node(1, 8, 2)
        peer.add(3)
        peer.sync_with((host, port), timeout=5.0)
        assert 3 in node.members()
        peer.close()
    finally:
        node.close()


def test_resync_epoch_fields_are_lock_guarded():
    """Pre-fix, clear_full_resync()/full_resync_done_for() touched the
    healing-epoch set with no lock; under the lockset detector that is
    an empty-lockset shared write.  Post-fix the detector stays silent
    and the flag reads go through the locked accessor."""
    from go_crdt_playground_tpu.net.peer import Node

    det = RaceDetector()
    node = det.instrument(Node(0, 8, 2))
    node.full_resync_pending = True  # arm via plain (traced) write

    def toggle():
        for _ in range(100):
            node.clear_full_resync()
            node.full_resync_is_pending()
            node.full_resync_done_for(("127.0.0.1", 1))

    _hammer(toggle)
    assert not [f for f in det.findings
                if "resync" in (f.symbol or "")], det.findings
    det.uninstall(node)


def test_supervisor_round_counter_locked():
    """checkpoint() reads _rounds_done under the supervisor lock now;
    the static lint pins it (L001 on regression), and the counter is
    still correct through a checkpointed round."""
    import tempfile

    from go_crdt_playground_tpu.net import Node, SyncSupervisor

    with tempfile.TemporaryDirectory() as d:
        a, b = Node(0, 8, 2), Node(1, 8, 2)
        with a, b:
            addr = b.serve()
            a.add(1)
            sup = SyncSupervisor(a, [addr], durable_dir=d,
                                 checkpoint_every=1, interval_s=0.0)
            sup.sync_round()
            assert a.generation >= 1, "checkpoint ran on the cadence"
            a.wal.close()


# ---------------------------------------------------------------------------
# T001: Thread-subclass attribute shadowing (the PR-12 _stop bug class)
# ---------------------------------------------------------------------------


def test_thread_shadow_finds_planted_offenders(tmp_path):
    from go_crdt_playground_tpu.analysis import thread_shadow

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "bad.py").write_text(
        "import threading\n"
        "from threading import Thread\n"
        "class Sampler(Thread):\n"
        "    def __init__(self):\n"
        "        super().__init__(daemon=True)\n"
        "        self._stop = threading.Event()  # breaks join()\n"
        "    def run(self):\n"
        "        pass\n"
        "class Pumper(threading.Thread):\n"
        "    def _bootstrap(self):  # overrides a runtime internal\n"
        "        pass\n"
        "    def start(self):  # shadows start() itself\n"
        "        pass\n")
    (pkg / "clean.py").write_text(
        "import threading\n"
        "class Good(threading.Thread):\n"
        "    def __init__(self):\n"
        "        super().__init__()\n"
        "        self._halt = threading.Event()  # renamed: fine\n"
        "        self.daemon = True              # property: fine\n"
        "        self.name = 'good'              # property: fine\n"
        "    def run(self):                      # documented override\n"
        "        pass\n"
        "class NotAThread:\n"
        "    def __init__(self):\n"
        "        self._stop = 1  # not a Thread subclass: fine\n")
    findings, stats = thread_shadow.analyze(str(pkg), extra_dirs=())
    assert stats["thread_subclasses"] == 3
    symbols = sorted(f.symbol for f in findings)
    assert symbols == ["Pumper._bootstrap", "Pumper.start",
                       "Sampler._stop"], symbols
    assert all(f.code == "T001" and f.severity == "error"
               for f in findings)
    # the exact PR-12 offender name is in the blocklist on this
    # interpreter (the pass derives it from threading.Thread itself)
    assert "_stop" in thread_shadow.thread_internal_names()


# ---------------------------------------------------------------------------
# the gate itself
# ---------------------------------------------------------------------------


def test_source_loader_caches_and_bypasses_for_planted():
    """One parse per file per gate run — and planted text (the
    sources= injection every ladder pass supports) must neither read
    nor poison the cache."""
    import os

    from go_crdt_playground_tpu.analysis.__main__ import PKG_ROOT
    from go_crdt_playground_tpu.analysis.loader import SourceLoader

    loader = SourceLoader()
    p = os.path.join(PKG_ROOT, "utils", "wal.py")
    a = loader.load(p)
    b = loader.load(p)
    assert a.tree is b.tree
    assert loader.stats() == {"files": 1, "hits": 1, "misses": 1}
    planted = loader.load(p, "x = 1\n")
    assert planted.source == "x = 1\n"
    assert loader.load(p).tree is a.tree, \
        "planted text must not replace the on-disk parse"
    assert loader.stats()["files"] == 1


def test_epoch_order_swapped_twin_detected():
    """E001 planted violation: announce before persist — the exact
    ordering the promotion spine forbids."""
    from go_crdt_playground_tpu.analysis import epoch_order
    from go_crdt_playground_tpu.analysis.epoch_order import OrderSpec

    src = (
        "class Standby:\n"
        "    def promote(self):\n"
        "        self.announce_epoch()\n"
        "        persist_router_epoch(self.dir, 1, 'sb')\n"
        "        self.serve()\n"
    )
    spec = OrderSpec("twin", "twin.py", "Standby.promote",
                     before=("persist_router_epoch",),
                     after=("announce_epoch", "serve"))
    f, s = epoch_order.analyze("/nowhere", specs=(spec,),
                               sources={"twin.py": src})
    assert len(f) == 1 and f[0].code == "E001", f
    assert "announce_epoch" in f[0].symbol
    assert s["ordered_points"] == 2  # serve() (dominated) was checked


def test_epoch_order_vanished_function_is_loud():
    """A registered promotion path that got renamed away must fail the
    gate, not silently un-check the contract."""
    from go_crdt_playground_tpu.analysis import epoch_order
    from go_crdt_playground_tpu.analysis.epoch_order import OrderSpec

    spec = OrderSpec("gone", "twin.py", "Standby.promote",
                     before=("persist",), after=("serve",))
    f, _ = epoch_order.analyze("/nowhere", specs=(spec,),
                               sources={"twin.py": "x = 1\n"})
    assert len(f) == 1 and f[0].code == "E001"
    assert "no longer exists" in f[0].message


def test_fence_coverage_unfenced_verb_detected():
    """E002 planted violation: a write-verb handler that consults no
    fence predicate and carries no fence-ok annotation."""
    from go_crdt_playground_tpu.analysis import fence_coverage
    from go_crdt_playground_tpu.analysis.fence_coverage import FenceSpec

    src = (
        "class FE:\n"
        "    def _dispatch(self, t, body):\n"
        "        if t == MSG_OP:\n"
        "            return self._handle_op(body)\n"
        "        if t == MSG_GC:\n"
        "            return self._handle_gc(body)\n"
        "    def _handle_op(self, body):\n"
        "        if self.shard_deposed():\n"
        "            return None\n"
        "        return 1\n"
        "    def _handle_gc(self, body):\n"
        "        return 2\n"
    )
    spec = FenceSpec("fe", "fe.py", "FE._dispatch",
                     write_verbs=("MSG_OP", "MSG_GC"),
                     predicates=("shard_deposed",))
    f, s = fence_coverage.analyze("/nowhere", specs=(spec,),
                                  sources={"fe.py": src})
    assert len(f) == 1 and f[0].code == "E002", f
    assert "MSG_GC" in f[0].symbol
    assert s["covered"] == 1  # MSG_OP passed


def test_fence_coverage_stale_annotation_detected():
    """A fence-ok on a handler that DOES consult the predicate is a
    stale annotation and fails the gate — an annotation that can never
    matter proves nothing."""
    from go_crdt_playground_tpu.analysis import fence_coverage
    from go_crdt_playground_tpu.analysis.fence_coverage import FenceSpec

    src = (
        "class FE:\n"
        "    def _dispatch(self, t, body):\n"
        "        if t == MSG_OP:\n"
        "            return self._handle_op(body)\n"
        "    # fence-ok: stale — the handler fences below\n"
        "    def _handle_op(self, body):\n"
        "        if self.shard_deposed():\n"
        "            return None\n"
        "        return 1\n"
    )
    spec = FenceSpec("fe", "fe.py", "FE._dispatch",
                     write_verbs=("MSG_OP",),
                     predicates=("shard_deposed",))
    f, _ = fence_coverage.analyze("/nowhere", specs=(spec,),
                                  sources={"fe.py": src})
    assert len(f) == 1 and f[0].code == "E002"
    assert "stale fence-ok" in f[0].message


def test_transfer_under_lock_detected_and_annotation_clears():
    """D002 planted violation: a blocking device_get inside a
    with-lock block; the transfer-ok twin passes."""
    from go_crdt_playground_tpu.analysis import transfer_lock

    src = (
        "import jax\n"
        "class T:\n"
        "    def pull(self):\n"
        "        with self._lock:\n"
        "            x = jax.device_get(self._state)\n"
        "        return x\n"
    )
    f, s = transfer_lock.analyze_paths(["t.py"],
                                       sources={"t.py": src})
    assert len(f) == 1 and f[0].code == "D002", f
    assert s["lock_held"] == 1 and s["transfer_ok"] == 0
    ok = src.replace(
        "            x = jax.device_get(self._state)",
        "            # transfer-ok: one bounded pull\n"
        "            x = jax.device_get(self._state)")
    f2, s2 = transfer_lock.analyze_paths(["t.py"],
                                         sources={"t.py": ok})
    assert not f2 and s2["transfer_ok"] == 1


def test_transfer_lock_fixpoint_reaches_called_helper():
    """The lock context propagates through the call graph: a helper
    that pulls, called from a with-lock block, is flagged even though
    it contains no lock itself (the framing.py shape)."""
    from go_crdt_playground_tpu.analysis import transfer_lock

    src = (
        "import jax\n"
        "def encode(state):\n"
        "    return jax.device_get(state)\n"
        "class T:\n"
        "    def append(self):\n"
        "        with self._lock:\n"
        "            return encode(self._state)\n"
    )
    f, s = transfer_lock.analyze_paths(["t.py"],
                                       sources={"t.py": src})
    assert len(f) == 1 and f[0].code == "D002", f
    assert f[0].symbol == "encode"
    assert s["lock_context_fns"] >= 1


def test_gate_fast(tmp_path):
    """The tier-1 hook: the full --fast gate must exit 0 on this tree
    and cover every registered pass in ANALYSIS_REPORT.json
    (acceptance criterion of the analyzer + protocol-contract
    ISSUEs)."""
    import json

    from go_crdt_playground_tpu.analysis.__main__ import main

    out = str(tmp_path / "ANALYSIS_REPORT.json")
    rc = main(["--fast", "--out", out])
    with open(out) as f:
        report = json.load(f)
    assert rc == 0, report
    assert report["ok"] and report["n_errors"] == 0
    assert {"lockdiscipline", "locksets", "durability", "purity",
            "lattice_laws"} <= set(report["passes"])
    # the runtime pass must have actually exercised instrumented objects
    assert report["passes"]["locksets"]["stats"]["fields_tracked"] > 0
    # the PR-5 serving frontend's shared state is inside the gate: its
    # classes must appear in the lock-discipline sweep (acceptance
    # criterion of the serve ISSUE — "0 findings on the serve/ locks"
    # only means something if serve/ was actually covered)
    covered = set(report["passes"]["lockdiscipline"]["stats"]
                  ["classes_by_name"])
    assert {"AdmissionQueue", "Session", "MicroBatcher", "ServeFrontend",
            "ServeClient"} <= covered, covered
    # ... and the shard/ router tier (the sharded-fleet ISSUE): ring,
    # router + its per-shard links/relays, and the fleet runner are all
    # multi-threaded shared state inside the same sweep
    assert {"HashRing", "ShardRouter", "_ShardLink", "_Relay",
            "ShardFleet", "ShardProc", "RouterProc"} <= covered, covered
    # ... and the live-resharding machinery (the dynamic-ring ISSUE):
    # the handoff coordinator + route snapshots, and the shared conn
    # host both endpoints now ride — all handoff state is lock- or
    # race-ok-annotated and swept
    assert {"HandoffCoordinator", "RouteState", "ConnHost"} <= covered, \
        covered
    # ... and the serve-ladder compaction scheduler (the throughput-
    # ladder ISSUE): its scheduling state crosses the loop thread and
    # the frontend's lifecycle thread
    assert "CompactionScheduler" in covered, covered
    # ... and the digest-sync tier (the digest anti-entropy ISSUE):
    # the per-peer negotiation cache crosses the supervisor's round
    # thread and any caller marking a peer legacy
    assert "DigestNegotiator" in covered, covered
    # ... and the device-mesh replica tier (the mesh ISSUE): the mesh
    # target's compiled-program caches and re-pin paths run under the
    # node lock across batcher/sync/compaction threads
    assert "MeshApplyTarget" in covered, covered
    # ... and the 2-D dp×mp tier (the 2-D mesh ISSUE): the striping
    # planner + chunked apply loop run under the node lock like every
    # other state mutation
    assert "Mesh2DApplyTarget" in covered, covered
    # ... and the fleet autopilot (the control-loop ISSUE): the
    # controller loop thread, signal poller, standby pool, actuator,
    # and the per-peer adaptive digest-group tuner are all inside the
    # sweep — "0 findings on control/" only means something if the
    # classes were actually covered
    assert {"FleetAutopilot", "AutopilotPolicy", "ReshardActuator",
            "FleetSignals", "StandbyPool"} <= covered, covered
    assert "AdaptiveGroupSize" in covered, covered
    # ... and the router-HA tier (the router-HA ISSUE): the standby's
    # tail loop, promotion path, and observer readers cross threads on
    # the standby lock and must be inside the sweep
    assert "RouterStandby" in covered, covered
    # ... and the shard replication tier (the shard-replication ISSUE):
    # the publisher's condition crosses WAL_SYNC readers with the
    # batcher's ack gate, the shard standby's tail loop crosses
    # promote()/observers, and both serving ladders poll the shared
    # degrade-window latch cross-thread
    assert {"ReplicationPublisher", "ShardStandby",
            "DegradeWindow"} <= covered, covered
    # ... and the conflict-aware admission scheduler (the hot-key
    # ISSUE): owned by the batcher loop thread, race-ok-annotated
    # read-only config — the sweep keeps those annotations honest
    assert "ConflictScheduler" in covered, covered
    # the wire-contract suite (the protocol-contract ISSUE): W001-W004
    # + M001 must have swept the dialect modules, every registered
    # dispatcher, the full codec registry, and the metric-name surface
    assert {"protocol_contract", "codec_symmetry", "metrics_contract",
            "report_freshness", "thread_shadow"} <= set(report["passes"])
    # T001 swept a real census (the tree is full of Thread subclasses;
    # zero scanned would mean the pass ran against nothing)
    ts = report["passes"]["thread_shadow"]["stats"]
    assert ts["thread_subclasses"] >= 3 and ts["files_scanned"] > 50, ts
    pc = report["passes"]["protocol_contract"]["stats"]
    assert set(pc["dispatchers"]) == {"frontend", "router", "peer",
                                      "serve-client"}, pc
    for d in pc["dispatchers"].values():
        assert d["required"], d  # no dispatcher checked an empty set
    assert pc["recv_frame_sites"] >= 9, pc
    assert pc["reject_sites"] >= 16, pc
    assert pc["codes"] >= 9, pc  # REJECT_STALE_SHARD_EPOCH included
    cs = report["passes"]["codec_symmetry"]["stats"]
    # the WAL_SYNC / SHARD_FAILOVER codec pairs (shard replication)
    # are registered alongside everything prior
    assert cs["codecs"] >= 28 and cs["codec_functions"] >= 48, cs
    mc = report["passes"]["metrics_contract"]["stats"]
    assert mc["emitted"] >= 60 and mc["referenced"] >= 20, mc
    # model-merging joins ride the lattice pass with their declared
    # law subsets (never a skip)
    laws = report["passes"]["lattice_laws"]["stats"]["laws_by_family"]
    assert laws["tensor_mean"] == ["commutativity"], laws
    assert laws["weighted_mean"] == ["commutativity",
                                     "associativity"], laws
    # freshness: the gate itself verified the committed artifact
    # matches the registered pass list
    rf = report["passes"]["report_freshness"]["stats"]
    assert set(rf["registered"]) == set(report["passes"]), rf
    # the protocol verification ladder (the verification-ladder ISSUE):
    # E001 checked every registered promotion spine, E002 resolved
    # every registered write verb, D002 swept the transfer sites, and
    # the model checker exhausted all three protocol models
    assert {"epoch_order", "fence_coverage", "transfer_lock",
            "protomodel"} <= set(report["passes"])
    eo = report["passes"]["epoch_order"]["stats"]
    assert eo["specs"] >= 4 and eo["ordered_points"] >= 10, eo
    fc = report["passes"]["fence_coverage"]["stats"]
    assert fc["write_verbs"] >= 9 and fc["covered"] >= 6, fc
    # exactly the adjudication verbs carry fence-ok (frontend
    # RING_SYNC + WAL_SYNC, router RING_SYNC) — a fourth would mean an
    # unfenced write verb was annotated away instead of fenced
    assert fc["fence_ok"] == 3, fc
    tl = report["passes"]["transfer_lock"]["stats"]
    assert tl["transfer_calls"] >= 5 and tl["lock_held"] >= 5, tl
    assert tl["transfer_ok"] == tl["lock_held"], tl
    pm = report["passes"]["protomodel"]["stats"]
    assert set(pm["models"]) == {"router_ha", "shard_repl",
                                 "handoff"}, pm
    for name, m in pm["models"].items():
        assert m["complete"], (name, m)  # exhausted, not capped
        assert m["violations"] == 0, (name, m)
        assert m["states"] >= 10, (name, m)
    assert pm["fresh"] == pm["mirrored_symbols"] >= 10, pm
    # run metadata: wall time + shared-parse-cache stats are recorded
    # top-level (meta is not a pass — rf["registered"] above proved
    # the pass list itself is unpolluted)
    meta = report["meta"]
    assert meta["fast"] is True and meta["wall_time_s"] > 0, meta
    assert meta["parse_cache"]["hits"] > meta["parse_cache"]["files"], \
        meta  # the cache actually deduped re-parses across passes


def test_report_shape_roundtrips(tmp_path):
    from go_crdt_playground_tpu.analysis.report import Finding, Report

    r = Report()
    r.add_stats("demo", files=1)
    r.extend([Finding(analyzer="demo", code="X001", severity="error",
                      message="planted", path="p.py", line=3)])
    out = tmp_path / "r.json"
    r.write_json(str(out))
    import json

    d = json.loads(out.read_text())
    assert d["ok"] is False and d["n_errors"] == 1
    assert d["passes"]["demo"]["findings"][0]["line"] == 3


# ---------------------------------------------------------------------------
# slow: the soak-integrated detector runs
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_chaos_soak_with_race_detection(tmp_path):
    """The --detect-races chaos soak: converges, stays race-free, and
    records the detector verdict in the curve artifact."""
    import json
    import os
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    import chaos_soak

    out = str(tmp_path / "CHAOS_CURVE.json")
    rc = chaos_soak.main(["--quick", "--detect-races", "--out", out])
    assert rc == 0
    with open(out) as f:
        artifact = json.load(f)
    assert artifact["race_detection"]["enabled"]
    assert artifact["race_detection"]["races"] == []
