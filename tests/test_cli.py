"""Demo CLI (python -m go_crdt_playground_tpu): the reference's go-test
walkthrough and a converging fleet, as shell commands."""

from go_crdt_playground_tpu.__main__ import main


def test_scenario_command_passes():
    assert main(["scenario"]) == 0


def test_gossip_command_converges():
    assert main(["gossip", "--replicas", "8"]) == 0
