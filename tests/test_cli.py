"""Demo CLI (python -m go_crdt_playground_tpu): the reference's go-test
walkthrough, a converging fleet, and the Merger bridge service — the
whole operational surface, driven as a user would."""

import os
import re
import signal
import subprocess
import sys

from go_crdt_playground_tpu.__main__ import main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_scenario_command_passes(capsys):
    assert main(["scenario"]) == 0
    out = capsys.readouterr().out
    # the walkthrough must actually demonstrate the property, spec and
    # packed alike, with the canonical Go rendering
    assert "add-wins holds: True" in out
    assert '(B 1)  "Bob"' in out  # the concurrent re-add's dot survives
    assert out.count("[(A 2), (B 1)]") >= 2  # spec A and B agree on VVs


def test_gossip_command_converges(capsys):
    assert main(["gossip", "--replicas", "8"]) == 0
    out = capsys.readouterr().out
    assert re.search(
        r"8 replicas \(full-state gossip\) converged in \d+ "
        r"dissemination rounds", out)


def test_gossip_command_delta_with_drops_converges(capsys):
    """The resilience story from the shell: delta semantics + lossy
    exchanges still converge (SURVEY §5.3 — drops only delay)."""
    assert main(["gossip", "--replicas", "8", "--delta",
                 "--drop-rate", "0.3"]) == 0
    out = capsys.readouterr().out
    assert re.search(
        r"8 replicas \(delta gossip under 30% drop\) converged in \d+ "
        r"dissemination rounds", out)


def test_serve_command_end_to_end(tmp_path):
    """`python -m go_crdt_playground_tpu serve` as a real subprocess:
    parse the printed address, ping, run one merge through the packed
    kernels over TCP, then SIGINT for a clean exit."""
    import queue
    import threading

    from __graft_entry__ import _scrubbed_cpu_env
    from go_crdt_playground_tpu.bridge.service import MergerClient
    from go_crdt_playground_tpu.models.spec import AWSet, VersionVector

    # stderr to a file (nothing to drain, content survives for
    # diagnostics); the address line is read under a hard deadline so a
    # child wedged before printing can never hang the suite
    err_path = tmp_path / "serve.err"
    with open(err_path, "w") as err_f:
        proc = subprocess.Popen(
            [sys.executable, "-m", "go_crdt_playground_tpu", "serve",
             "--port", "0"],
            env=_scrubbed_cpu_env(1),  # never dial the TPU tunnel from CI
            cwd=REPO,  # the package is not pip-installed
            stdout=subprocess.PIPE, stderr=err_f, text=True)
    try:
        lines: "queue.Queue[str]" = queue.Queue()
        threading.Thread(target=lambda: lines.put(proc.stdout.readline()),
                         daemon=True).start()
        try:
            line = lines.get(timeout=120)
        except queue.Empty:
            raise AssertionError(
                "serve printed no address within 120s; stderr:\n"
                + err_path.read_text()[-3000:])
        m = re.search(r"listening on ([\d.]+):(\d+)", line)
        assert m, (f"no address line: {line!r}; stderr:\n"
                   + err_path.read_text()[-3000:])
        host, port = m.group(1), int(m.group(2))
        with MergerClient(host, port, timeout=120.0) as client:
            assert client.ping()
            a = AWSet(actor=0, version_vector=VersionVector([0, 0]))
            b = AWSet(actor=1, version_vector=VersionVector([0, 0]))
            a.add("Anne")
            b.add("Bob")
            merged = client.merge(a, b)
            assert merged.sorted_values() == ["Anne", "Bob"]
        proc.send_signal(signal.SIGINT)
        assert proc.wait(timeout=30) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)


def test_serve_ingest_command_end_to_end(tmp_path):
    """`serve --ingest` as a real subprocess: parse the address, submit
    ops through the serve client, read membership back, then SIGTERM
    for a graceful drain (the drain summary line is the contract the
    serve soak's parent also reads)."""
    from __graft_entry__ import _scrubbed_cpu_env
    from go_crdt_playground_tpu.serve import ServeClient

    err_path = tmp_path / "ingest.err"
    with open(err_path, "w") as err_f:
        proc = subprocess.Popen(
            [sys.executable, "-m", "go_crdt_playground_tpu", "serve",
             "--ingest", "--elements", "64", "--actors", "2",
             "--durable-dir", str(tmp_path / "n0"), "--flush-ms", "1"],
            env=_scrubbed_cpu_env(1), cwd=REPO,
            stdout=subprocess.PIPE, stderr=err_f, text=True)
    try:
        import queue
        import threading

        lines: "queue.Queue[str]" = queue.Queue()
        threading.Thread(target=lambda: lines.put(proc.stdout.readline()),
                         daemon=True).start()
        try:
            line = lines.get(timeout=120)
        except queue.Empty:
            raise AssertionError(
                "serve --ingest printed no address within 120s; stderr:\n"
                + err_path.read_text()[-3000:])
        m = re.search(r"listening on ([\d.]+):(\d+)", line)
        assert m, (f"no address line: {line!r}; stderr:\n"
                   + err_path.read_text()[-3000:])
        with ServeClient((m.group(1), int(m.group(2))),
                         timeout=120.0) as client:
            client.add(1, 2, 3)
            client.delete(2)
            members, vv = client.members()
        assert members == [1, 3]
        assert int(vv[0]) == 4  # 3 add ticks + 1 del tick
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=60)
        assert proc.returncode == 0
        assert re.search(r"drained: 2 ops acked, ingest p99 ", out), out
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)


def test_gossip_command_rejects_certain_loss():
    """--drop-rate 1.0 can never converge; the parser fails fast with a
    clean error instead of grinding the full round budget."""
    import pytest

    with pytest.raises(SystemExit) as exc:
        main(["gossip", "--drop-rate", "1.0"])
    assert exc.value.code == 2  # argparse usage error


def test_serve_ingest_rejects_malformed_peer():
    """--peer without a port is a clean argparse error, not an int('')
    traceback at startup."""
    import pytest

    with pytest.raises(SystemExit) as exc:
        main(["serve", "--ingest", "--peer", "otherhost"])
    assert exc.value.code == 2


def test_gossip_command_seed_flag(capsys):
    """--seed feeds the drop-mask PRNG so shell users can sample
    independent loss realizations (ADVICE r4); every seed still
    converges (drops only delay convergence, SURVEY §5.3)."""
    assert main(["gossip", "--replicas", "8", "--drop-rate", "0.3",
                 "--seed", "7"]) == 0
    assert "converged in" in capsys.readouterr().out


def test_gossip_command_schedule_flag(capsys):
    """--schedule exposes the library's pairing schedules from the
    shell; the random schedule derives its pairings from --seed."""
    assert main(["gossip", "--replicas", "8",
                 "--schedule", "random", "--seed", "5"]) == 0
    assert "random rounds" in capsys.readouterr().out
    assert main(["gossip", "--replicas", "8", "--schedule", "ring"]) == 0
    assert "ring rounds" in capsys.readouterr().out


def test_platform_flag_pins_backend(capsys):
    """--platform cpu pins the backend in-process (the axon TPU plugin
    ignores JAX_PLATFORMS, so this flag is the only way the CLI stays
    usable when the remote tunnel is down).  Asserting the config value
    pins the wiring itself — under the conftest the scenario would pass
    even without the pin."""
    import jax

    assert main(["--platform", "cpu", "scenario"]) == 0
    assert jax.config.jax_platforms == "cpu"
    assert "add-wins holds: True" in capsys.readouterr().out
