"""One process of the host-fleet soak test (tests/test_fleet_soak.py).

Runs a single networked replica (net.peer.Node) through a lossy-fleet
anti-entropy schedule: phase 1 syncs through the parent's lossy proxies
(drops surface as socket errors — anti-entropy self-heals, SURVEY §5.3),
phase 2 sweeps every peer directly so the final digests must agree.

Protocol on stdio (parent = tests/test_fleet_soak.py):
  -> "PORT <p>"            after the node's server is up
  <- "ADDRS <2n ports>"    n proxy ports then n direct ports
  -> "PHASE1"              after the lossy sweeps
  <- "PHASE2"              all workers finished phase 1
  -> "PHASE2DONE"          after the clean all-pairs sweep
  <- "REPORT"              all workers finished phase 2 (no sync can
                           mutate state after this point)
  -> one JSON line {"members": [...], "vv": [...]}
"""

import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    idx, n, num_elements = (int(a) for a in sys.argv[1:4])
    from go_crdt_playground_tpu.net import Node

    node = Node(idx, num_elements, n, conn_timeout_s=5.0)
    node.add(*range(idx * 4, idx * 4 + 4))  # private element slice
    _, port = node.serve()
    print(f"PORT {port}", flush=True)

    parts = sys.stdin.readline().split()
    assert parts[0] == "ADDRS", parts
    ports = [int(p) for p in parts[1:]]
    proxy, direct = ports[:n], ports[n:]

    rng = random.Random(1000 + idx)
    lost = 0
    for _sweep in range(4):
        order = [j for j in range(n) if j != idx]
        rng.shuffle(order)  # reordering: every sweep hits peers anew
        for j in order:
            # duplication: a repeated exchange must be idempotent
            dials = 2 if rng.random() < 0.15 else 1
            for _ in range(dials):
                try:
                    node.sync_with(("127.0.0.1", proxy[j]), timeout=4.0)
                except Exception:
                    lost += 1  # a lost exchange, never lost data
    print("PHASE1", flush=True)
    assert sys.stdin.readline().strip() == "PHASE2"

    # clean direct sweep: after every pair exchanged at least once
    # post-quiescence, all replicas hold the global union
    for j in range(n):
        if j == idx:
            continue
        for _attempt in range(40):
            try:
                node.sync_with(("127.0.0.1", direct[j]), timeout=4.0)
                break
            except Exception:
                time.sleep(0.1)
        else:
            print(f"FAIL unreachable {j}", flush=True)
            return 1
    print("PHASE2DONE", flush=True)
    assert sys.stdin.readline().strip() == "REPORT"
    print(json.dumps({
        "members": [int(e) for e in node.members()],
        "vv": [int(v) for v in node.vv()],
        "lost": lost,
    }), flush=True)
    node.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
