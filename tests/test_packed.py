"""Bitpacked membership layout (models/packed.py + the packed ring
kernels): bitwise conformance against the bool layout.

SURVEY §7.1/§7.3 step 5 — ``present``/``deleted`` as uint32[R, E/32].
The contract: pack -> packed ring round -> unpack must equal the bool
ring round bitwise, so the packed layout is a pure storage change,
never a semantics change.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from go_crdt_playground_tpu.models import packed as packed_mod
from go_crdt_playground_tpu.models.awset import AWSetState
from go_crdt_playground_tpu.ops import pallas_delta, pallas_merge
from go_crdt_playground_tpu.parallel import gossip

R = 2 * pallas_merge._BLOCK_R


def rand_state(rng, num_r, num_e, num_a):
    present = rng.random((num_r, num_e)) < 0.5
    da = np.where(present, rng.integers(0, num_a, (num_r, num_e)),
                  0).astype(np.uint32)
    dc = np.where(present, rng.integers(1, 9, (num_r, num_e)),
                  0).astype(np.uint32)
    return AWSetState(
        vv=jnp.asarray(rng.integers(0, 10, (num_r, num_a))
                       .astype(np.uint32)),
        present=jnp.asarray(present), dot_actor=jnp.asarray(da),
        dot_counter=jnp.asarray(dc),
        actor=jnp.arange(num_r, dtype=jnp.uint32) % num_a)


@pytest.mark.parametrize("num_e", [32, 100, 256])
def test_pack_unpack_roundtrip(num_e):
    rng = np.random.default_rng(1)
    mask = jnp.asarray(rng.random((24, num_e)) < 0.4)
    bits = pallas_merge.pack_bits(mask)
    assert bits.shape == (24, (num_e + 31) // 32)
    np.testing.assert_array_equal(
        np.asarray(pallas_merge.unpack_bits(bits, num_e)),
        np.asarray(mask))


@pytest.mark.parametrize("offset", [1, 65, 127])
def test_packed_ring_round_matches_bool(offset):
    rng = np.random.default_rng(2)
    state = rand_state(rng, R, 256, 5)
    want = pallas_merge.pallas_ring_round_rows(state, offset)
    got_packed = pallas_merge.pallas_ring_round_rows_packed(
        packed_mod.pack_awset(state), offset)
    got = packed_mod.unpack_awset(got_packed, 256)
    for name in want._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(want, name)),
            np.asarray(getattr(got, name)), err_msg=name)


def test_packed_ring_round_ragged_e():
    """E not a multiple of 32 or 128: padded bits stay zero."""
    rng = np.random.default_rng(3)
    state = rand_state(rng, R, 200, 3)
    want = pallas_merge.pallas_ring_round_rows(state, 9)
    got_packed = pallas_merge.pallas_ring_round_rows_packed(
        packed_mod.pack_awset(state), 9)
    got = packed_mod.unpack_awset(got_packed, 200)
    for name in want._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(want, name)),
            np.asarray(getattr(got, name)), err_msg=name)


@pytest.mark.parametrize("offset", [1, 65])
def test_packed_delta_ring_round_matches_bool(offset):
    import random

    from tests.test_pallas_delta import _scenario_state

    rng = random.Random(44)
    state = _scenario_state(rng, R, 128, 8)
    want = pallas_delta.pallas_delta_ring_round(state, offset)
    got_packed = pallas_delta.pallas_delta_ring_round_packed(
        packed_mod.pack_awset_delta(state), offset)
    got = packed_mod.unpack_awset_delta(got_packed, 128)
    for name in want._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(want, name)),
            np.asarray(getattr(got, name)), err_msg=name)


def test_packed_schedule_stays_packed_and_converges():
    """A whole dissemination schedule on the packed layout (traced
    offsets, one program) converges to the bool layout's result."""
    rng = np.random.default_rng(5)
    state = rand_state(rng, R, 128, 4)
    offsets = jnp.asarray(gossip.dissemination_offsets(R), jnp.uint32)

    @jax.jit
    def run_packed(p):
        def body(c, off):
            return pallas_merge.pallas_ring_round_rows_packed(c, off), None
        return jax.lax.scan(body, p, offsets)[0]

    want = state
    for off in gossip.dissemination_offsets(R):
        want = gossip.gossip_round(want, gossip.ring_perm(R, off),
                                   kernel="xla")
    got = packed_mod.unpack_awset(
        run_packed(packed_mod.pack_awset(state)), 128)
    for name in want._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(want, name)),
            np.asarray(getattr(got, name)), err_msg=name)
    assert bool(gossip.converged_jit(got.present, got.vv))


def test_packed_nbytes_are_8x_smaller():
    """The storage win the layout exists for: membership bytes drop 8x
    (32 lanes per uint32 word vs 1 byte per bool lane)."""
    rng = np.random.default_rng(6)
    state = rand_state(rng, R, 256, 4)
    packed = packed_mod.pack_awset(state)
    assert packed.present_bits.nbytes * 8 == state.present.nbytes


@pytest.mark.parametrize("offset", [1, 64, 65])
def test_packed_ring_round_beyond_one_word_group(offset):
    """E=8192 -> 256 packed words, two 128-word lane groups: the word
    tiling (pallas_merge._packed_tiling) must produce bitwise-identical
    results to the bool layout beyond the old E<=4096 cap, on both the
    aligned (offset 64) and windowed kernel forms."""
    rng = np.random.default_rng(7)
    E = 8192
    state = rand_state(rng, R, E, 5)
    want = pallas_merge.pallas_ring_round_rows(state, offset)
    got_packed = pallas_merge.pallas_ring_round_rows_packed(
        packed_mod.pack_awset(state), offset)
    assert got_packed.present_bits.shape == (R, E // 32)
    got = packed_mod.unpack_awset(got_packed, E)
    for name in want._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(want, name)),
            np.asarray(getattr(got, name)), err_msg=name)


@pytest.mark.parametrize("offset", [1, 64])
def test_packed_delta_ring_round_beyond_one_word_group(offset):
    """The delta twin at E=8192 (word-tiled multi-j grid), v2 mode."""
    import random

    from tests.test_pallas_delta import _scenario_state

    rng = random.Random(13)
    E = 8192
    state = _scenario_state(rng, R, E, 8)
    want = pallas_delta.pallas_delta_ring_round(state, offset)
    got_packed = pallas_delta.pallas_delta_ring_round_packed(
        packed_mod.pack_awset_delta(state), offset)
    got = packed_mod.unpack_awset_delta(got_packed, E)
    for name in want._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(want, name)),
            np.asarray(getattr(got, name)), err_msg=name)


def test_packed_non_chunk_multiple_width():
    """E between one and two chunks (4100 elements -> 129 words): the
    padded word tail must round-trip exactly."""
    rng = np.random.default_rng(9)
    E = 4100
    state = rand_state(rng, R, E, 4)
    want = pallas_merge.pallas_ring_round_rows(state, 3)
    got_packed = pallas_merge.pallas_ring_round_rows_packed(
        packed_mod.pack_awset(state), 3)
    assert got_packed.present_bits.shape == (R, (E + 31) // 32)
    got = packed_mod.unpack_awset(got_packed, E)
    for name in want._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(want, name)),
            np.asarray(getattr(got, name)), err_msg=name)


@pytest.mark.parametrize("offset", [1, 65, 64, 127])
def test_dotpacked_ring_round_matches_bool(offset):
    """The dot-word layout (one uint32 per element: actor<<20|counter,
    plus bitpacked membership) must be invisible in results: the fused
    ring round agrees bitwise with the bool layout through
    pack/unpack, on both the windowed and aligned kernel forms."""
    rng = np.random.default_rng(21)
    state = rand_state(rng, R, 256, 5)
    want = pallas_merge.pallas_ring_round_rows(state, offset)
    got_packed = pallas_merge.pallas_ring_round_rows_dotpacked(
        packed_mod.pack_awset_dots(state), offset)
    got = packed_mod.unpack_awset_dots(got_packed, 256)
    for name in want._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(want, name)),
            np.asarray(getattr(got, name)), err_msg=name)


def test_dotpacked_roundtrip_and_schedule_converges():
    rng = np.random.default_rng(23)
    state = rand_state(rng, R, 96, 8)
    rt = packed_mod.unpack_awset_dots(
        packed_mod.pack_awset_dots(state), 96)
    for name in state._fields:
        np.testing.assert_array_equal(np.asarray(getattr(state, name)),
                                      np.asarray(getattr(rt, name)),
                                      err_msg=name)
    # stays in the packed domain across a whole dissemination schedule
    p = packed_mod.pack_awset_dots(state)
    for off in gossip.dissemination_offsets(R):
        p = pallas_merge.pallas_ring_round_rows_dotpacked(p, off)
    out = packed_mod.unpack_awset_dots(p, 96)
    ref = state
    for off in gossip.dissemination_offsets(R):
        ref = pallas_merge.pallas_ring_round_rows(ref, off)
    for name in ref._fields:
        np.testing.assert_array_equal(np.asarray(getattr(ref, name)),
                                      np.asarray(getattr(out, name)),
                                      err_msg=name)


def test_dotpacked_beyond_one_word_group():
    """Word-axis tiling (E > 4096) on the dot-word kernel."""
    rng = np.random.default_rng(27)
    state = rand_state(rng, R, 4100, 7)
    for offset in (3, 64):
        want = pallas_merge.pallas_ring_round_rows(state, offset)
        got = packed_mod.unpack_awset_dots(
            pallas_merge.pallas_ring_round_rows_dotpacked(
                packed_mod.pack_awset_dots(state), offset), 4100)
        for name in want._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(want, name)),
                np.asarray(getattr(got, name)),
                err_msg=f"{offset}/{name}")


def test_dotpacked_pack_guards():
    """The 20-bit counter / 12-bit actor caps must refuse loudly at
    pack time — an overflowed counter would alias a neighbouring
    actor's bits and corrupt merges silently."""
    rng = np.random.default_rng(29)
    state = rand_state(rng, R, 32, 8)
    big = state._replace(dot_counter=state.dot_counter.at[0, 0].set(
        jnp.uint32(packed_mod.DOT_MAX_COUNTER + 1)))
    with pytest.raises(ValueError, match="counter"):
        packed_mod.pack_awset_dots(big)
    wide = rand_state(rng, R, 32, 5000)
    with pytest.raises(ValueError, match="actor bits"):
        packed_mod.pack_awset_dots(wide)


@pytest.mark.parametrize("offset", [1, 64, 65, 127])
def test_dotpacked_delta_ring_round_matches_bool(offset):
    """The δ dot-word ring (both dot pairs as single words + bitpacked
    membership) must agree bitwise with the bool-layout δ ring through
    pack/unpack — windowed and aligned kernel forms."""
    import random

    from tests.test_pallas_delta import _scenario_state

    rng = random.Random(71)
    state = _scenario_state(rng, R, 128, 8)
    want = pallas_delta.pallas_delta_ring_round(state, offset)
    got_packed = pallas_delta.pallas_delta_ring_round_dotpacked(
        packed_mod.pack_awset_delta_dots(state), offset)
    got = packed_mod.unpack_awset_delta_dots(got_packed, 128)
    for name in want._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(want, name)),
            np.asarray(getattr(got, name)), err_msg=name)


def test_dotpacked_delta_schedule_stays_packed_and_converges():
    """A full dissemination schedule in the dot-word domain matches the
    bool-layout schedule bitwise and converges."""
    import random

    from go_crdt_playground_tpu.parallel import collectives
    from tests.test_pallas_delta import _scenario_state

    rng = random.Random(73)
    state = _scenario_state(rng, R, 96, 8)
    p = packed_mod.pack_awset_delta_dots(state)
    ref = state
    for off in gossip.dissemination_offsets(R):
        p = pallas_delta.pallas_delta_ring_round_dotpacked(p, off)
        ref = pallas_delta.pallas_delta_ring_round(ref, off)
    out = packed_mod.unpack_awset_delta_dots(p, 96)
    for name in ref._fields:
        np.testing.assert_array_equal(np.asarray(getattr(ref, name)),
                                      np.asarray(getattr(out, name)),
                                      err_msg=name)
    assert bool(collectives.converged(out.present, out.vv))


def test_dotpacked_delta_pack_guards():
    import random

    from tests.test_pallas_delta import _scenario_state

    rng = random.Random(79)
    state = _scenario_state(rng, R, 32, 8)
    big = state._replace(del_dot_counter=state.del_dot_counter.at[
        0, 0].set(jnp.uint32(packed_mod.DOT_MAX_COUNTER + 1)))
    with pytest.raises(ValueError, match="counter"):
        packed_mod.pack_awset_delta_dots(big)


def test_dotpacked_traced_offset_schedule_matches_static():
    """Production schedules feed offsets as DATA (one compiled program,
    lax.cond aligned/windowed dispatch); the traced path must equal the
    per-offset static calls for both dot-word kernels."""
    import random

    import jax

    from tests.test_pallas_delta import _scenario_state

    rng = np.random.default_rng(31)
    st = packed_mod.pack_awset_dots(rand_state(rng, R, 96, 8))
    offs = jnp.asarray([3, 64, 65], jnp.uint32)

    @jax.jit
    def sched(s):
        def body(c, o):
            return pallas_merge.pallas_ring_round_rows_dotpacked(c, o), None
        return jax.lax.scan(body, s, offs)[0]

    want = st
    for o in (3, 64, 65):
        want = pallas_merge.pallas_ring_round_rows_dotpacked(want, o)
    got = sched(st)
    for name in want._fields:
        np.testing.assert_array_equal(np.asarray(getattr(got, name)),
                                      np.asarray(getattr(want, name)),
                                      err_msg=name)

    rngd = random.Random(33)
    dst = packed_mod.pack_awset_delta_dots(_scenario_state(rngd, R, 96, 8))

    @jax.jit
    def dsched(s):
        def body(c, o):
            return pallas_delta.pallas_delta_ring_round_dotpacked(c, o), None
        return jax.lax.scan(body, s, offs)[0]

    dwant = dst
    for o in (3, 64, 65):
        dwant = pallas_delta.pallas_delta_ring_round_dotpacked(dwant, o)
    dgot = dsched(dst)
    for name in dwant._fields:
        np.testing.assert_array_equal(np.asarray(getattr(dgot, name)),
                                      np.asarray(getattr(dwant, name)),
                                      err_msg=f"delta/{name}")


def test_dotpacked_ring_round_matches_spec_directly():
    """Triangulation independent of the bool-kernel chain: random op
    histories on 128 SPEC replicas, one ring round executed (a) by the
    dict-model spec merges and (b) by the dot-word kernel on the packed
    fleet, compared through byte-equal canonical renderings."""
    import random

    from go_crdt_playground_tpu.models.spec import AWSet, VersionVector
    from go_crdt_playground_tpu.models import awset as awset_mod
    from go_crdt_playground_tpu.utils import codec

    rng = random.Random(91)
    Rn, E, A = R, 48, R  # R=128 replicas, one actor each
    spec = [AWSet(actor=r, version_vector=VersionVector([0] * A))
            for r in range(Rn)]
    dictionary = codec.ElementDict(
        capacity=E, values=[f"e{i}" for i in range(E)])
    for r in range(Rn):
        for _ in range(rng.randrange(1, 6)):
            k = f"e{rng.randrange(E)}"
            if rng.random() < 0.75:
                spec[r].add(k)
            elif k in spec[r].entries:
                spec[r].del_(k)
    packed = packed_mod.pack_awset_dots(awset_mod.from_arrays(
        codec.pack_awsets(spec, dictionary, A)))

    offset = 65  # windowed form; exercises the roll path
    got = packed_mod.unpack_awset_dots(
        pallas_merge.pallas_ring_round_rows_dotpacked(packed, offset), E)
    for r in range(Rn):  # spec merges use PRE-round partner states
        spec[r] = spec[r].clone()
    pre = [s.clone() for s in spec]
    for r in range(Rn):
        spec[r].merge(pre[(r + offset) % Rn])
    rendered = codec.render_packed(
        {"vv": np.asarray(got.vv), "present": np.asarray(got.present),
         "dot_actor": np.asarray(got.dot_actor),
         "dot_counter": np.asarray(got.dot_counter),
         "actor": np.asarray(got.actor)}, dictionary)
    assert rendered == [str(s) for s in spec]


@pytest.mark.parametrize("offset", [1, 64])
@pytest.mark.parametrize("semantics,strict", [("reference", True),
                                              ("reference", False)])
def test_dotpacked_delta_ring_reference_modes_match_bool(offset, semantics,
                                                         strict):
    """The dot-word δ ring under STRICT-REFERENCE semantics (incl. the
    empty-δ VV-skip scratch epilogue) and the loose variant must match
    the bool-layout kernel bitwise — the quirk machinery is
    layout-independent."""
    import random

    from tests.test_pallas_delta import _scenario_state

    rng = random.Random(97)
    state = _scenario_state(rng, R, 128, 8)
    want = pallas_delta.pallas_delta_ring_round(
        state, offset, delta_semantics=semantics,
        strict_reference_semantics=strict)
    got = packed_mod.unpack_awset_delta_dots(
        pallas_delta.pallas_delta_ring_round_dotpacked(
            packed_mod.pack_awset_delta_dots(state), offset,
            delta_semantics=semantics, strict_reference_semantics=strict),
        128)
    for name in want._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(want, name)),
            np.asarray(getattr(got, name)), err_msg=name)


@pytest.mark.parametrize("strict", [True, False])
def test_packed_delta_ring_reference_modes_match_bool(strict):
    """The bitpacked δ ring under the reference semantics modes matches
    the bool-layout kernel bitwise (symmetry with the dot-word wrapper)."""
    import random

    from tests.test_pallas_delta import _scenario_state

    rng = random.Random(101)
    state = _scenario_state(rng, R, 128, 8)
    for offset in (1, 64):
        want = pallas_delta.pallas_delta_ring_round(
            state, offset, delta_semantics="reference",
            strict_reference_semantics=strict)
        got = packed_mod.unpack_awset_delta(
            pallas_delta.pallas_delta_ring_round_packed(
                packed_mod.pack_awset_delta(state), offset,
                delta_semantics="reference",
                strict_reference_semantics=strict), 128)
        for name in want._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(want, name)),
                np.asarray(getattr(got, name)),
                err_msg=f"{offset}/{name}")
