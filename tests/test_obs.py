"""Observability (obs/): trace rendering conformance + metrics.

The key property: rendering the kernel's MergeTrace decision tensors and
the spec model's TraceEvents for the SAME scenario yields the same line
set (Go's map-iteration line order is nondeterministic, reference
SURVEY §5.1, so comparison is on sorted lines)."""

import numpy as np
import pytest

from go_crdt_playground_tpu.models import awset
from go_crdt_playground_tpu.models.spec import (AWSet, Dot, TraceEvent,
                                                VersionVector)
from go_crdt_playground_tpu.obs import (Recorder, format_event,
                                        payload_metrics, render_spec_trace,
                                        render_tensor_trace, trace_counts)
from go_crdt_playground_tpu.ops.merge import merge_pairwise
from go_crdt_playground_tpu.utils import codec

E = 16


def key(i: int) -> str:
    return f"e{i:02d}"


def run_scenario():
    """Two replicas, ops chosen so merge A<-B hits update (both present,
    different dots), add (B-only unseen), remove (A-only entry B
    witnessed and deleted), and phase-2 keep."""
    a = AWSet(actor=0, version_vector=VersionVector([0, 0]))
    b = AWSet(actor=1, version_vector=VersionVector([0, 0]))
    a.add(key(1), key(2), key(3))
    b.merge(a)            # b now shares 1,2,3 (same dots -> keep lanes)
    b.del_(key(3))        # b witnessed 3 and removed it -> remove lane
    b.add(key(2))         # fresh dot for 2 at B -> update lane
    b.add(key(4))         # B-only -> add lane
    a.del_(key(1))
    b.add(key(1))         # hmm: A deleted 1 but B re-adds with new dot
    return a, b


def packed_pair(a: AWSet, b: AWSet):
    dictionary = codec.ElementDict(capacity=E)
    for i in range(E):
        dictionary.encode(key(i))
    arrays = codec.pack_awsets([a, b], dictionary, num_actors=2)
    return awset.from_arrays(arrays), dictionary


def test_tensor_trace_matches_spec_trace():
    a, b = run_scenario()
    events = []
    a.trace = events.append
    state, dictionary = packed_pair(a, b)

    # spec merge a <- b (collects events)
    a.merge(b)

    # kernel merge row0 <- row1 with trace
    import jax

    dst = jax.tree.map(lambda x: x[:1], state)
    src = jax.tree.map(lambda x: x[1:], state)
    merged, trace = merge_pairwise(dst, src, with_trace=True)

    spec_lines = render_spec_trace(events)
    tensor_lines = render_tensor_trace(
        jax.tree.map(lambda x: x[0], trace),
        jax.tree.map(lambda x: x[0], dst),
        jax.tree.map(lambda x: x[0], src),
        key_of=dictionary.decode,
        header=False,
    )
    assert sorted(tensor_lines) == sorted(spec_lines)
    # and the merged state agrees with the spec replica
    np.testing.assert_array_equal(
        np.nonzero(np.asarray(merged.present[0]))[0],
        sorted(dictionary.encode(k) for k in a.entries),
    )


@pytest.mark.parametrize("semantics", ["reference", "v2"])
def test_delta_tensor_trace_matches_spec_trace(semantics):
    """δ-path parity: the packed δ-apply's decision tensors render to the
    same line set as the spec AWSetDelta's deltaMerge logging
    (awset-delta_test.go:113-163), in both δ semantics."""
    import jax

    from go_crdt_playground_tpu.models import awset_delta as delta_mod
    from go_crdt_playground_tpu.models.spec import AWSetDelta
    from go_crdt_playground_tpu.obs import render_delta_tensor_trace
    from go_crdt_playground_tpu.ops import delta as delta_ops

    a = AWSetDelta(actor=0, version_vector=VersionVector([0, 0]),
                   delta_semantics=semantics)
    b = AWSetDelta(actor=1, version_vector=VersionVector([0, 0]),
                   delta_semantics=semantics)
    a.add(key(1), key(2), key(3))
    b.merge(a)                 # first contact: full branch, untraced
    a.del_(key(3))             # deletion record -> phase-2 lane
    a.add(key(2))              # fresh dot at A -> update lane at B
    a.add(key(4))              # A-only -> add lane
    b.add(key(5))              # B-only local entry, untouched
    b.del_(key(1))             # B deleted 1; A's record for 1? none - keep

    # packed twin BEFORE the traced exchange
    dictionary = codec.ElementDict(capacity=E)
    for i in range(E):
        dictionary.encode(key(i))
    arrays = codec.pack_awset_deltas([a, b], dictionary, 2)
    packed = delta_mod.from_arrays(arrays)

    events = []
    b.trace = events.append
    b.merge(a)                 # spec δ branch, collects log events

    src = jax.tree.map(lambda x: x[0], packed)   # A
    dst = jax.tree.map(lambda x: x[1], packed)   # B
    payload = delta_ops.delta_extract(src, dst.vv)
    merged, trace = delta_ops.delta_apply_traced(
        dst, payload, delta_semantics=semantics)

    spec_lines = render_spec_trace(events)
    tensor_lines = render_delta_tensor_trace(
        trace, dst, payload, key_of=dictionary.decode, header=False,
        delta_semantics=semantics)
    assert sorted(tensor_lines) == sorted(spec_lines)
    # and the applied state matches the spec receiver's membership
    np.testing.assert_array_equal(
        np.nonzero(np.asarray(merged.present))[0],
        sorted(dictionary.encode(k) for k in b.entries),
    )


def test_line_format_is_go_identical():
    # awset.go:120: fmt.Printf("> phase %d %-10q %-18s => %s\n", ...)
    ev_line = format_event(TraceEvent(1, "Anne", Dot(0, 1), Dot(1, 2),
                                      "update"))
    assert ev_line == '> phase 1 "Anne"     (A 1) <- (B 2)     => update'
    ev_line = format_event(TraceEvent(2, "Bob", Dot(2, 7), None, "remove"))
    assert ev_line == '> phase 2 "Bob"      (C 7) <- ()        => remove'


def test_trace_counts_all_outcomes():
    a, b = run_scenario()
    state, _ = packed_pair(a, b)
    import jax

    dst = jax.tree.map(lambda x: x[:1], state)
    src = jax.tree.map(lambda x: x[1:], state)
    _, trace = merge_pairwise(dst, src, with_trace=True)
    counts = trace_counts(trace)
    assert counts["phase1"].get("update", 0) >= 1
    assert counts["phase1"].get("add", 0) >= 1
    assert counts["phase2"].get("remove", 0) >= 1
    assert counts["phase2"].get("keep", 0) >= 1


def test_recorder():
    r = Recorder()
    r.count("merges", 5)
    r.count("merges", 3)
    r.observe("payload_bytes", 10)
    r.observe("payload_bytes", 30)
    with r.time("round_s"):
        pass
    snap = r.snapshot()
    assert snap["counters"]["merges"] == 8
    o = snap["observations"]["payload_bytes"]
    assert (o["n"], o["sum"], o["min"], o["max"], o["mean"]) == (
        2, 40.0, 10.0, 30.0, 20.0)
    assert snap["observations"]["round_s"]["n"] == 1


def test_recorder_percentiles_log_spaced_histogram():
    """observe() keeps a BOUNDED log-spaced histogram so percentile()
    reports real p50/p95/p99 (within the √2 bucket error), not max —
    the serve frontend's SLO numbers ride this."""
    r = Recorder()
    # 98 fast observations + 2 slow outliers: p50 must sit near the
    # fast mode, p99 near (but never above) the outliers — the old
    # n/sum/min/max summary could only ever report 2.0 here
    for _ in range(98):
        r.observe("lat_s", 0.001)
    r.observe("lat_s", 2.0)
    r.observe("lat_s", 2.0)
    p50 = r.percentile("lat_s", 0.50)
    p99 = r.percentile("lat_s", 0.99)
    assert 0.001 <= p50 <= 0.001 * 2 ** 0.5
    assert 1.0 < p99 <= 2.0
    # snapshot carries the derived quantiles alongside the summary
    o = r.snapshot()["observations"]["lat_s"]
    assert o["p50"] == p50 and o["p99"] == p99 and o["p95"] <= o["p99"]
    # identical values report exactly (clamped to observed min/max)
    r2 = Recorder()
    for _ in range(10):
        r2.observe("x", 0.25)
    assert r2.percentile("x", 0.5) == 0.25
    assert r2.percentile("x", 0.99) == 0.25


def test_recorder_percentile_edges():
    import pytest as _pytest

    r = Recorder()
    with _pytest.raises(KeyError):
        r.percentile("never", 0.5)  # no data must not read as 0 latency
    r.observe("edge", 0.0)       # underflow bucket
    r.observe("edge", 1e9)       # overflow bucket
    assert r.percentile("edge", 0.0) <= 1e-6  # underflow bucket bound
    assert r.percentile("edge", 1.0) == 1e9   # overflow reports exact max
    with _pytest.raises(ValueError):
        r.percentile("edge", 1.5)


def test_payload_metrics():
    import jax
    import jax.numpy as jnp

    from go_crdt_playground_tpu.models import awset_delta
    from go_crdt_playground_tpu.ops import delta as delta_ops

    state = awset_delta.init(1, E, 2)
    state = awset_delta.add_element(state, jnp.uint32(0), jnp.uint32(3))
    state = awset_delta.add_element(state, jnp.uint32(0), jnp.uint32(5))
    me = jax.tree.map(lambda x: x[0], state)
    p = delta_ops.delta_extract(me, jnp.zeros(2, jnp.uint32))
    m = payload_metrics(p)
    assert m["changed_lanes"] == 2
    assert m["deleted_lanes"] == 0
    assert 0 < m["wire_bytes"] < m["dense_bytes"]


def test_printstate_box_dump_parity():
    """The fixtures' boxed dump (awset_test.go:169-174): 48-em-dash rule,
    'Replica A: %s' lines with the canonical String — byte-identical for
    the 2-replica fixture shape, and the tensor path's render_packed
    strings drop in for the spec renderings."""
    from go_crdt_playground_tpu.obs import printstate

    a = AWSet(actor=0, version_vector=VersionVector([0, 0]))
    b = AWSet(actor=1, version_vector=VersionVector([0, 0]))
    a.add("Anne", "Bob")
    b.merge(a)
    b.del_("Bob")
    out = printstate([a, b])
    rule = "—" * 48
    expected = (f"{rule}\n"
                f"Replica A: {a}\n"
                f"Replica B: {b}\n"
                f"{rule}\n")
    assert out == expected
    # the packed tensor path renders identically (codec canonical String)
    dictionary = codec.ElementDict(capacity=4)
    packed = awset.from_arrays(codec.pack_awsets([a, b], dictionary, 2))
    rendered = codec.render_packed(awset.to_arrays(packed), dictionary)
    assert printstate(rendered) == expected


def test_delta_extract_print_parity():
    """The sender-side extraction print (awset-delta_test.go:103) renders
    byte-for-byte from both the spec model and the tensor payload: the
    T6 scenario's own two extraction moments are the oracle (Go fmt
    prints map[string]Dot with sorted keys; nil maps as map[])."""
    from go_crdt_playground_tpu.models.spec import AWSetDelta, VersionVector
    from go_crdt_playground_tpu.obs import (format_delta_extract,
                                            format_delta_extract_tensor)
    from go_crdt_playground_tpu.ops import delta as delta_ops
    from go_crdt_playground_tpu.utils.codec import (ElementDict,
                                                    pack_awset_deltas)
    import jax
    import jax.numpy as jnp

    A = AWSetDelta(actor=0, version_vector=VersionVector([0, 0]))
    B = AWSetDelta(actor=1, version_vector=VersionVector([0, 0]))
    A.add("A", "B"); B.add("A", "C")
    A.merge(B); B.merge(A)
    A.del_("B"); A.add("D", "E"); B.add("E")

    # B.Merge(A)'s extraction: A ships D/E adds + the B deletion record
    changed, deleted = A.make_delta_merge_data(B.version_vector)
    line = format_delta_extract(changed, deleted)
    assert line == ("delta: changed map[D:(A 4) E:(A 5)], "
                    "deleted map[B:(A 3)]"), line

    # same line from the packed tensor payload
    dictionary = ElementDict(capacity=8)
    arrays = pack_awset_deltas([A, B], dictionary, 2)
    from go_crdt_playground_tpu.models import awset_delta as ad
    state = ad.from_arrays(arrays)
    src = jax.tree.map(lambda x: x[0], state)   # A is replica 0
    payload = delta_ops.delta_extract(src, jnp.asarray(state.vv[1]))
    tline = format_delta_extract_tensor(payload, key_of=dictionary.decode)
    assert tline == line, (tline, line)

    # after convergence B->A extracts nothing, but A->B still ships its
    # deletion record (reference mode has no GC, so records persist —
    # the nil-map rendering and the asymmetry are both pinned)
    B.merge(A)
    changed, deleted = B.make_delta_merge_data(A.version_vector)
    assert format_delta_extract(changed, deleted) == \
        "delta: changed map[], deleted map[]"
    changed, deleted = A.make_delta_merge_data(B.version_vector)
    assert format_delta_extract(changed, deleted) == \
        "delta: changed map[], deleted map[B:(A 3)]"
