"""Conformance gate for the fused Pallas δ-gossip kernel.

ops/pallas_delta.py must be bitwise-identical to the XLA δ path
(ops/delta.py v2 dispatch), which tests/test_delta_kernel.py pins to the
executable spec — equality here transitively pins the fused kernel to
the reference δ semantics (awset-delta_test.go:51-166)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from go_crdt_playground_tpu.models import awset_delta
from go_crdt_playground_tpu.ops import pallas_delta
from go_crdt_playground_tpu.parallel import gossip


def _assert_equal(want, got, ctx=""):
    for name in want._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(want, name)), np.asarray(getattr(got, name)),
            err_msg=f"{ctx}:{name}")


def _scenario_state(rng, R, E, A):
    """Mixed history: adds, deletions (records), re-adds (resurrections),
    plus some rows that never wrote (first-contact sources)."""
    # observer topology when A < R: the aliased trailing rows never write
    st = awset_delta.init(R, E, A, actors=np.arange(R) % A)
    writers = min(A, max(1, R - 2))
    for _ in range(5 * R):
        r = rng.randrange(writers)                # trailing rows stay silent
        e = rng.randrange(E)
        roll = rng.random()
        if roll < 0.6:
            st = awset_delta.add_element(st, np.uint32(r), np.uint32(e))
        else:
            sel = np.zeros(E, bool)
            sel[e] = True
            if rng.random() < 0.3:                # multi-key Del call
                sel[rng.randrange(E)] = True
            st = awset_delta.del_elements(st, np.uint32(r), np.asarray(sel))
    return st


@pytest.mark.parametrize(
    "R,E,A",
    [
        (8, 16, 8),       # exact blocks
        (7, 300, 5),      # ragged everything
        (12, 640, 16),    # multiple E tiles, R pads to 16
    ],
)
def test_fused_delta_round_matches_xla(R, E, A):
    import random
    rng = random.Random(101)
    st = _scenario_state(rng, R, E, A)
    for offset in (1, 2, 3):
        perm = gossip.ring_perm(R, offset)
        want = gossip.delta_gossip_round(st, perm, delta_semantics="v2",
                                         kernel="xla")
        got = pallas_delta.pallas_delta_gossip_round(st, perm)
        _assert_equal(want, got, f"offset {offset}")
        st = want   # iterate on merged state (first contacts become delta)


def test_fused_delta_first_contact_rows():
    """Rows whose receiver never saw the sender take the full branch."""
    import random
    rng = random.Random(103)
    st = _scenario_state(rng, 8, 32, 8)
    # fresh state: every exchange is first contact
    perm = gossip.ring_perm(8, 1)
    want = gossip.delta_gossip_round(st, perm, delta_semantics="v2",
                                     kernel="xla")
    got = pallas_delta.pallas_delta_gossip_round(st, perm)
    _assert_equal(want, got, "all-first-contact")


def test_fused_delta_large_counters_exact():
    st = awset_delta.init(6, 64, 6)
    big = jnp.uint32(0xFFFE0007)
    st = st._replace(
        vv=st.vv.at[0, 0].set(big).at[1, 1].set(big + 8),
        present=st.present.at[0, 3].set(True),
        dot_actor=st.dot_actor.at[0, 3].set(0),
        dot_counter=st.dot_counter.at[0, 3].set(big),
        processed=st.processed.at[0, 0].set(big),
    )
    perm = gossip.ring_perm(6, 1)
    want = gossip.delta_gossip_round(st, perm, delta_semantics="v2",
                                     kernel="xla")
    got = pallas_delta.pallas_delta_gossip_round(st, perm)
    _assert_equal(want, got, "large counters")


def test_fused_delta_equal_counter_deletion_tiebreak():
    """Equal-counter deletion records from DIFFERENT actors must take
    the (counter, actor) lexicographic max in the Pallas kernel exactly
    as in XLA (ops/delta._delta_apply_impl) — counter-only absorb kept
    whichever record arrived first, so opposite ring directions left
    replicas' deletion-log lanes permanently divergent (the lane-never-
    silent pathology the digest regime's bitwise pin exposes)."""
    E = 32
    st = awset_delta.init(4, E, 4)
    # rows 0 and 1: both delete element 7 with counter 5, actors 0/1
    for row, actor in ((0, 0), (1, 1)):
        st = st._replace(
            vv=st.vv.at[row, actor].set(5),
            deleted=st.deleted.at[row, 7].set(True),
            del_dot_actor=st.del_dot_actor.at[row, 7].set(actor),
            del_dot_counter=st.del_dot_counter.at[row, 7].set(5),
        )
    # two opposite round orders: the records arrive in different
    # sequence at each row, yet every row must land on the SAME
    # (counter=5, actor=1) lexicographic max — and each round stays
    # bitwise-pinned to XLA
    for order in ((1, 2, 3), (3, 2, 1)):
        cur = st
        for offset in order:
            perm = gossip.ring_perm(4, offset)
            want = gossip.delta_gossip_round(
                cur, perm, delta_semantics="v2", kernel="xla")
            got = pallas_delta.pallas_delta_gossip_round(cur, perm)
            _assert_equal(want, got, f"tiebreak order {order} "
                                     f"offset {offset}")
            cur = want
        for row in range(4):
            assert int(np.asarray(cur.del_dot_counter)[row, 7]) == 5, \
                (order, row)
            assert int(np.asarray(cur.del_dot_actor)[row, 7]) == 1, \
                (order, row)


def test_delta_dispatch_guard():
    st = awset_delta.init(4, 8, 4)
    with pytest.raises(ValueError):
        pallas_delta.pallas_delta_gossip_round(
            st, gossip.ring_perm(4, 1), delta_semantics="v3")


# ---------------------------------------------------------------------------
# Strict-reference semantics (fused empty-δ VV-skip quirk)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "R,E,A",
    [
        (8, 16, 8),       # exact blocks
        (7, 300, 5),      # ragged everything
        (12, 640, 16),    # multiple E tiles (quirk reduction spans them)
    ],
)
@pytest.mark.parametrize("strict", [True, False])
def test_fused_delta_reference_matches_xla(R, E, A, strict):
    """Reference-mode fused kernel vs the XLA reference path, iterated
    so first-contact, δ, and steady-state (empty-payload) rounds all
    occur (awset-delta_test.go:51-65 incl. the :60-64 quirk)."""
    import random
    rng = random.Random(131)
    st_x = _scenario_state(rng, R, E, A)
    st_p = st_x
    for offset in (1, 2, 3, 1, 2, 3):   # repeats drive payloads empty
        perm = gossip.ring_perm(R, offset)
        st_x = gossip.delta_gossip_round(
            st_x, perm, delta_semantics="reference",
            strict_reference_semantics=strict, kernel="xla")
        st_p = pallas_delta.pallas_delta_gossip_round(
            st_p, perm, delta_semantics="reference",
            strict_reference_semantics=strict)
        _assert_equal(st_x, st_p, f"offset {offset} strict={strict}")


def test_fused_delta_reference_empty_payload_skips_vv():
    """The quirk itself: entries converged, VVs divergent, payloads
    empty -> strict mode must NOT join the vv (the reference's [5,2] vs
    [5,3] clock divergence, SURVEY §3.3), loose mode must."""
    st = awset_delta.init(8, 16, 8)
    # all replicas know element 0 via dot (0, 1) and have seen EVERY
    # actor tick once (nonzero partner counters — otherwise the round
    # takes the first-contact FULL branch, which always joins,
    # awset-delta_test.go:53-56); clocks diverge in own slots only, so
    # every pairwise payload is empty (receiver covers dot (0,1))
    vv = np.ones((8, 8), np.uint32)
    vv[np.arange(8), np.arange(8)] += np.arange(8).astype(np.uint32)
    st = st._replace(
        vv=jnp.asarray(vv),
        present=st.present.at[:, 0].set(True),
        dot_actor=st.dot_actor.at[:, 0].set(0),
        dot_counter=st.dot_counter.at[:, 0].set(1))
    perm = gossip.ring_perm(8, 1)
    want = gossip.delta_gossip_round(st, perm,
                                     delta_semantics="reference",
                                     kernel="xla")
    got = pallas_delta.pallas_delta_gossip_round(
        st, perm, delta_semantics="reference")
    _assert_equal(want, got, "empty-payload quirk")
    # strict: vv unchanged (the skip); loose: vv joined
    np.testing.assert_array_equal(np.asarray(got.vv), vv)
    loose = pallas_delta.pallas_delta_gossip_round(
        st, perm, delta_semantics="reference",
        strict_reference_semantics=False)
    assert not np.array_equal(np.asarray(loose.vv), vv)
    want_loose = gossip.delta_gossip_round(
        st, perm, delta_semantics="reference",
        strict_reference_semantics=False, kernel="xla")
    _assert_equal(want_loose, loose, "loose join")


@pytest.mark.parametrize("offset", [1, 63, 64, 128])
def test_delta_ring_reference_matches_xla(offset):
    """Ring-fused reference-mode kernel (aligned and windowed offsets)
    vs the XLA reference path."""
    import random

    from go_crdt_playground_tpu.ops import pallas_merge

    rng = random.Random(137)
    num_r = 4 * pallas_merge._BLOCK_R
    st = _scenario_state(rng, num_r, 128, 8)
    for rep in range(2):   # second pass exercises empty payloads
        want = gossip.delta_gossip_round(
            st, gossip.ring_perm(num_r, offset),
            delta_semantics="reference", kernel="xla")
        got = pallas_delta.pallas_delta_ring_round(
            st, offset, delta_semantics="reference")
        _assert_equal(want, got, f"ring ref offset {offset} rep {rep}")
        st = want


def test_fused_delta_converges_like_xla():
    import random
    rng = random.Random(107)
    st = _scenario_state(rng, 8, 32, 8)
    xla = gossip.all_pairs_converge(st, delta=True, delta_semantics="v2")
    pal = st
    for off in gossip.dissemination_offsets(8):
        pal = pallas_delta.pallas_delta_gossip_round(
            pal, gossip.ring_perm(8, off))
    _assert_equal(xla, pal, "converged fixed point")


@pytest.mark.parametrize("offset", [1, 63, 64, 65, 120])
def test_delta_ring_round_matches_xla(offset):
    """Ring-fused δ kernel (in-place partner windows) vs the XLA v2 δ
    round over the same ring perm: block-aligned, misaligned, and
    wraparound offsets."""
    import random

    from go_crdt_playground_tpu.ops import pallas_merge

    rng = random.Random(111)
    num_r = 2 * pallas_merge._BLOCK_R  # ring path needs aligned blocks
    st = _scenario_state(rng, num_r, 128, 8)
    want = gossip.delta_gossip_round(
        st, gossip.ring_perm(num_r, offset), delta_semantics="v2",
        kernel="xla")
    got = pallas_delta.pallas_delta_ring_round(st, offset)
    _assert_equal(want, got, f"ring offset {offset}")


def test_delta_ring_fallback_unaligned_rows():
    """R not a _BLOCK_R multiple falls back to the gather-path kernel
    with identical results."""
    import random

    rng = random.Random(112)
    st = _scenario_state(rng, 12, 64, 5)
    want = gossip.delta_gossip_round(
        st, gossip.ring_perm(12, 5), delta_semantics="v2", kernel="xla")
    got = pallas_delta.pallas_delta_ring_round(st, 5)
    _assert_equal(want, got, "fallback")


def test_delta_ring_gossip_round_dispatch_equal():
    """parallel.gossip.delta_ring_gossip_round: kernel choices and the
    drop-mask lane agree bitwise."""
    import random

    from go_crdt_playground_tpu.ops import pallas_merge

    rng = random.Random(113)
    num_r = 2 * pallas_merge._BLOCK_R
    st = _scenario_state(rng, num_r, 64, 8)
    drop = jnp.asarray(np.random.default_rng(0).random(num_r) < 0.3)
    want = gossip.delta_gossip_round(
        st, gossip.ring_perm(num_r, 5), drop, delta_semantics="v2",
        kernel="xla")
    for kernel in ("xla", "pallas"):
        got = gossip.delta_ring_gossip_round(st, 5, drop, kernel=kernel)
        _assert_equal(want, got, kernel)
