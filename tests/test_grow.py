"""Grow-and-repack (SURVEY §7.5.1): the fixed element universe's
overflow policy and the actor-axis extension, both exact by the
zero-padding semantics (crdt-misc.go:29-41)."""

import numpy as np
import pytest

from go_crdt_playground_tpu.models import awset, awset_delta
from go_crdt_playground_tpu.models.spec import AWSet, VersionVector
from go_crdt_playground_tpu.ops.merge import merge_one_into
from go_crdt_playground_tpu.utils import codec


def _two_writers(E=8):
    a = AWSet(actor=0, version_vector=VersionVector([0, 0]))
    b = AWSet(actor=1, version_vector=VersionVector([0, 0]))
    a.add("x", "y")
    b.add("y", "z")
    a.del_("y")
    d = codec.ElementDict(capacity=E)
    packed = awset.from_arrays(codec.pack_awsets([a, b], d, 2))
    return a, b, d, packed


def test_grow_elements_preserves_rendering_and_merge():
    a, b, d, packed = _two_writers()
    grown = codec.grow_elements(packed, 32)
    assert grown.present.shape[-1] == 32
    # rendering unchanged (padded lanes are absent)
    d32 = codec.ElementDict(capacity=32, values=[d.decode(i)
                                                 for i in range(len(d))])
    assert (codec.render_packed(awset.to_arrays(grown), d32)
            == codec.render_packed(awset.to_arrays(packed), d))
    # grow-then-merge == merge-then-grow, bitwise on the common lanes
    m_then_g = codec.grow_elements(merge_one_into(packed, 0, packed, 1)[0],
                                   32)
    g_then_m = merge_one_into(grown, 0, grown, 1)[0]
    for name in m_then_g._fields:
        np.testing.assert_array_equal(np.asarray(getattr(m_then_g, name)),
                                      np.asarray(getattr(g_then_m, name)),
                                      name)


def test_grow_universe_admits_new_keys():
    a, b, d, packed = _two_writers(E=4)
    # fill the dictionary to capacity, then overflow
    d.encode("w")
    assert len(d) <= 4
    with pytest.raises(OverflowError):
        for i in range(10):
            d.encode(f"spill{i}")
    grown = codec.grow_universe(d, packed)
    eid = d.encode("spill-ok")
    assert eid < d.capacity and grown.present.shape[-1] == d.capacity
    grown = awset.add_element(grown, np.uint32(0), np.uint32(eid))
    assert bool(grown.present[0, eid])


def test_grow_actors_exact():
    st = awset_delta.init(4, 8, 4)
    st = awset_delta.add_element(st, np.uint32(2), np.uint32(5))
    grown = codec.grow_actors(st, 16)
    assert grown.vv.shape == (4, 16) and grown.processed.shape == (4, 16)
    np.testing.assert_array_equal(np.asarray(grown.vv[:, :4]),
                                  np.asarray(st.vv))
    assert (np.asarray(grown.vv[:, 4:]) == 0).all()
    # element-shaped fields untouched
    np.testing.assert_array_equal(np.asarray(grown.present),
                                  np.asarray(st.present))


def test_grow_rejects_shrink():
    st = awset.init(2, 8, 2)
    with pytest.raises(ValueError):
        codec.grow_elements(st, 4)
    with pytest.raises(ValueError):
        codec.grow_actors(st, 1)
