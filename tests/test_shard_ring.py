"""Ring properties (shard/ring.py), pinned as the ISSUE demands:
seeded balance bound, minimal remap under membership change, and
routing determinism ACROSS PROCESSES — a router restart (or a second
router) must route every key identically or the fleet silently splits
its keyspaces.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from go_crdt_playground_tpu.shard.ring import (HashRing, load_stats,
                                               remap_fraction)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_ring_rejects_bad_config():
    with pytest.raises(ValueError):
        HashRing([])
    with pytest.raises(ValueError):
        HashRing(["a", "a"])
    with pytest.raises(ValueError):
        HashRing([""])
    with pytest.raises(ValueError):
        HashRing(["only"]).without_shard("only")
    with pytest.raises(ValueError):
        HashRing(["a"]).without_shard("missing")


def test_ring_owner_is_total_and_stable():
    r = HashRing(["s0", "s1", "s2"], seed=3)
    owners = r.owner_map(512)
    assert owners.shape == (512,)
    assert set(np.unique(owners)) <= {0, 1, 2}
    for e in (0, 7, 511):
        assert r.shards[owners[e]] == r.owner(e)
        assert r.owner_index(e) == owners[e]


def test_ring_ignores_shard_listing_order():
    """Two operators listing the same fleet in different --shard order
    must route identically."""
    a = HashRing(["s2", "s0", "s1"], seed=9)
    b = HashRing(["s0", "s1", "s2"], seed=9)
    assert a.shards == b.shards
    assert a.digest(256) == b.digest(256)


@pytest.mark.parametrize("n_shards,seed", [(2, 0), (3, 7), (5, 23)])
def test_ring_balance_bound(n_shards, seed):
    """Seeded balance: with E >> n the max/mean shard load stays near
    1 (rendezvous scores are i.i.d. uniform per (shard, key))."""
    E = 4096
    r = HashRing([f"s{i}" for i in range(n_shards)], seed=seed)
    stats = load_stats(r.owner_map(E), n_shards)
    assert all(x > 0 for x in stats["loads"])
    assert stats["max_over_mean"] < 1.15, stats
    assert stats["min_over_mean"] > 0.85, stats


def test_ring_minimal_remap_on_join_and_leave():
    """HRW's exact minimal-remap property: a join moves ONLY keys into
    the joiner (an expected 1/(n+1) fraction), a leave moves ONLY the
    leaver's keys — zero gratuitous moves either way."""
    E = 4096
    r3 = HashRing(["s0", "s1", "s2"], seed=11)
    r4 = r3.with_shard("s3")
    m3, m4 = r3.owner_map(E), r4.owner_map(E)
    join = remap_fraction(m3, m4, r3.shards, r4.shards)
    assert join["gratuitous"] == []
    # expected 1/4; well under double it, well over half it
    assert 0.125 < join["fraction"] < 0.5, join
    # a leave is the exact inverse membership change
    back = r4.without_shard("s3")
    assert back.shards == r3.shards
    leave = remap_fraction(m4, back.owner_map(E), r4.shards, back.shards)
    assert leave["gratuitous"] == []
    assert leave["moved"] == join["moved"]


def test_ring_seed_changes_placement_not_balance():
    E = 2048
    a = HashRing(["s0", "s1", "s2"], seed=1)
    b = HashRing(["s0", "s1", "s2"], seed=2)
    assert a.digest(E) != b.digest(E)
    assert load_stats(b.owner_map(E), 3)["max_over_mean"] < 1.2


def test_ring_determinism_across_processes():
    """Same (shards, seed, E) ⇒ same owner map in a FRESH interpreter:
    the ``router`` CLI's dry-run mode prints the digest this process
    computes.  This is the property that lets a restarted router (or a
    second one) serve the same fleet without remapping a single key."""
    E, seed = 384, 17
    ring = HashRing(["s0", "s1", "s2"], seed=seed)
    argv = [sys.executable, "-m", "go_crdt_playground_tpu", "router",
            "--elements", str(E), "--seed", str(seed)]
    for sid in ("s1", "s0", "s2"):  # permuted on purpose
        argv += ["--shard", f"{sid}=127.0.0.1:1"]
    out = subprocess.run(
        argv, cwd=REPO, capture_output=True, text=True, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert out.returncode == 0, out.stderr
    assert f"owner-map digest {ring.digest(E)} " in out.stdout, out.stdout


# ---------------------------------------------------------------------------
# edge cases (the live-resharding ISSUE's satellite: the ring math the
# handoff plan leans on must behave at the boundaries)
# ---------------------------------------------------------------------------


def test_with_shard_duplicate_id_refused():
    r = HashRing(["a", "b"])
    with pytest.raises(ValueError):
        r.with_shard("a")


def test_without_shard_down_to_one_then_refuses():
    r = HashRing(["a", "b", "c"], seed=1)
    r = r.without_shard("b").without_shard("c")
    assert r.shards == ("a",)
    assert all(r.owner(e) == "a" for e in range(16))
    with pytest.raises(ValueError):
        r.without_shard("a")


def test_remap_fraction_identical_rings_is_zero():
    r = HashRing(["a", "b", "c"], seed=7)
    owners = r.owner_map(128)
    rm = remap_fraction(owners, owners, r.shards, r.shards)
    assert rm == {"moved": 0, "fraction": 0.0, "gratuitous": []}


def test_remap_fraction_disjoint_rings_moves_everything():
    """A full fleet replacement moves every key, and every move is
    FORCED (out of a leaver, into a joiner) — gratuitous stays []."""
    before = HashRing(["a", "b"], seed=7)
    after = HashRing(["x", "y"], seed=7)
    rm = remap_fraction(before.owner_map(64), after.owner_map(64),
                        before.shards, after.shards)
    assert rm["moved"] == 64
    assert rm["fraction"] == 1.0
    assert rm["gratuitous"] == []


def test_load_stats_small_universes():
    # E=1: one shard owns the lone element, the rest own nothing
    r = HashRing(["a", "b", "c"], seed=0)
    owners = r.owner_map(1)
    stats = load_stats(owners, 3)
    assert sorted(stats["loads"]) == [0, 0, 1]
    assert stats["max_over_mean"] == pytest.approx(3.0)
    assert stats["min_over_mean"] == 0.0
    # E < n: loads still sum to E and the helper never divides by zero
    owners = r.owner_map(2)
    stats = load_stats(owners, 3)
    assert sum(stats["loads"]) == 2
    # single shard: trivially perfectly balanced
    solo = HashRing(["only"]).owner_map(8)
    stats = load_stats(solo, 1)
    assert stats["loads"] == [8]
    assert stats["max_over_mean"] == stats["min_over_mean"] == 1.0


def test_handoff_plan_covers_exactly_the_forced_moves():
    """The transfer work list is the remap, grouped by directed pair:
    a join's recipients are all the joiner, a leave's donors all the
    leaver, and the union of the plan's slices is exactly the moved
    set."""
    from go_crdt_playground_tpu.shard.ring import handoff_plan

    E = 256
    before = HashRing(["s0", "s1", "s2"], seed=11)
    after = before.with_shard("s3")
    ob, oa = before.owner_map(E), after.owner_map(E)
    plan = handoff_plan(ob, oa, before.shards, after.shards)
    assert plan, "a join must move a nonzero slice (E >> n)"
    assert all(dst == "s3" for _, dst, _ in plan)
    moved_in_plan = sorted(e for _, _, elems in plan for e in elems)
    rm = remap_fraction(ob, oa, before.shards, after.shards)
    assert len(moved_in_plan) == rm["moved"]
    assert moved_in_plan == sorted(
        e for e in range(E) if before.shards[ob[e]] != after.shards[oa[e]])
    # leave: same, reversed — every donor is the leaver
    plan_back = handoff_plan(oa, ob, after.shards, before.shards)
    assert all(src == "s3" for src, _, _ in plan_back)
    assert sorted(e for _, _, elems in plan_back
                  for e in elems) == moved_in_plan
