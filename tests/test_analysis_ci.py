"""CI/tooling half of the analyzer gate (DESIGN.md §15).

``test_gate_fast`` in tests/test_analysis.py runs the project-specific
invariant passes; this file covers the generic tooling: the ``ruff``
baseline configured in pyproject.toml (skipped where ruff is not
installed — the container image does not ship it; the config is the
contract, CI images that have ruff enforce it), and the repo-root
``tools/analyze.py`` wrapper staying in lockstep with the module CLI.
"""

import importlib.util
import json
import os
import re
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ruff_cmd():
    """How to invoke ruff HERE, or None if this environment has none.
    Two resolution paths, because the dev extra installs ruff as a
    module that is not necessarily a PATH binary: the ``ruff``
    executable if present, else ``python -m ruff`` when the module is
    importable.  The old PATH-only probe half-skipped: an environment
    with the dev extra installed into a venv (module importable, no
    binary on PATH) silently skipped the baseline it could have run."""
    if shutil.which("ruff") is not None:
        return ["ruff"]
    if importlib.util.find_spec("ruff") is not None:
        return [sys.executable, "-m", "ruff"]
    return None


def test_ruff_baseline_is_configured():
    # text-level check (tomllib lands in 3.11; this image runs 3.10)
    with open(os.path.join(REPO, "pyproject.toml")) as f:
        cfg = f.read()
    assert "[tool.ruff" in cfg
    assert '"F82"' in cfg, \
        "undefined-name checking is the floor of the ruff baseline"
    # the dev extra is how a contributor GETS ruff (the skip message of
    # test_ruff_baseline_clean points at it; keep the two in lockstep)
    assert "[project.optional-dependencies]" in cfg
    assert re.search(r'dev\s*=\s*\[\s*"ruff', cfg), \
        "pyproject must carry a dev extra providing ruff"


@pytest.mark.skipif(
    _ruff_cmd() is None,
    reason="ruff is absent from this environment (no `ruff` binary on "
           "PATH and no importable module) — this image does not ship "
           "the dev extra; installing it, `pip install -e '.[dev]'`, "
           "provides ruff, and CI images that have it enforce the "
           "baseline")
def test_ruff_baseline_clean():
    proc = subprocess.run(_ruff_cmd() + ["check", "."], cwd=REPO,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_gate_json_summary_contract(tmp_path):
    """--json prints ONE machine-readable line (CI parses it) with the
    same exit-code contract as the human mode: 0 iff no ERROR
    finding.  Both directions are exercised — the clean tree, and a
    run whose committed-report check is pointed at a stale artifact."""
    out = str(tmp_path / "rep.json")
    stale = tmp_path / "stale_committed.json"
    stale.write_text(json.dumps({"passes": {"lockdiscipline": {}}}))
    base = [sys.executable, "-m", "go_crdt_playground_tpu.analysis",
            "--fast", "--skip-runtime", "--json", "--out", out]
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    ok = subprocess.run(base, cwd=REPO, capture_output=True, text=True,
                        timeout=600, env=env)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    lines = [ln for ln in ok.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, ok.stdout  # one summary line, no prose
    summary = json.loads(lines[0])
    assert summary["ok"] is True and summary["errors"] == 0
    assert summary["model_states"] > 0
    assert summary["out"] == out
    assert "protomodel" in summary["passes"]

    bad = subprocess.run(base + ["--committed-report", str(stale)],
                         cwd=REPO, capture_output=True, text=True,
                         timeout=600, env=env)
    assert bad.returncode == 1, bad.stdout + bad.stderr
    summary = json.loads(bad.stdout.strip().splitlines()[-1])
    assert summary["ok"] is False and summary["errors"] >= 1


def test_gate_fast_stays_under_budget(tmp_path):
    """The --fast gate must stay inside its recorded wall-time
    envelope (meta.fast_budget_s): tier-1 runs it on every push, so a
    pass going quadratic — or a model scope exploding past small-scope
    exhaustiveness — shows up here as a hard failure, not as slow
    drift nobody bisects."""
    from go_crdt_playground_tpu.analysis.__main__ import (FAST_BUDGET_S,
                                                          main)

    out = str(tmp_path / "rep.json")
    rc = main(["--fast", "--out", out])
    assert rc == 0
    with open(out) as f:
        meta = json.load(f)["meta"]
    assert meta["fast_budget_s"] == FAST_BUDGET_S
    assert meta["wall_time_s"] < FAST_BUDGET_S, (
        f"--fast gate took {meta['wall_time_s']}s, budget "
        f"{FAST_BUDGET_S}s — a pass regressed its complexity or a "
        "model scope grew; shrink it or justify a new budget")


def test_tools_analyze_wrapper(tmp_path):
    """The repo-root wrapper must produce the same report the module
    CLI does, defaulting the artifact next to the other curves when
    --out is omitted (here: explicit tmp out, fast mode)."""
    out = str(tmp_path / "ANALYSIS_REPORT.json")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "analyze.py"),
         "--fast", "--skip-runtime", "--out", out],
        cwd=REPO, capture_output=True, text=True, timeout=600,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    with open(out) as f:
        report = json.load(f)
    assert report["ok"]
    assert report["passes"]["locksets"]["stats"]["mode"] == "skipped"
