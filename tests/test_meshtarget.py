"""Device-mesh replica tier (parallel/meshtarget.py, DESIGN.md §20).

The correctness story is BITWISE: a ``MeshApplyTarget`` fed the same
batches as a plain single-device ``Node`` must produce identical state,
identical WAL record bytes, identical digest summaries, and identical
slice-transfer payloads — on every mesh size, including the 1-device
degenerate case.  The multi-device coverage is real: tests/conftest.py
forces ``--xla_force_host_platform_device_count=8`` before jax loads,
and ``test_mesh_tests_saw_multiple_devices`` pins that the flag
actually took (skip-not-pass when absent, so a stripped-down runner
can't silently demote every mesh test to single-device).
"""

import os

import numpy as np
import pytest

import jax

from go_crdt_playground_tpu.net.peer import Node
from go_crdt_playground_tpu.parallel.meshtarget import (BATCH_AXIS,
                                                        MeshApplyTarget,
                                                        make_batch_mesh)

E, A, B = 1024, 4, 8


def _random_batches(rng, n, e=E, add_p=0.01, del_p=0.005):
    for _ in range(n):
        yield (rng.random((B, e)) < add_p,
               rng.random((B, e)) < del_p,
               rng.random(B) < 0.85)


def _assert_states_equal(a, b, context=""):
    for name in a._fields:
        xa, xb = np.asarray(getattr(a, name)), np.asarray(getattr(b, name))
        assert np.array_equal(xa, xb), (context, name, xa, xb)


# ---------------------------------------------------------------------------
# the multi-device guarantee itself
# ---------------------------------------------------------------------------


def test_mesh_tests_saw_multiple_devices():
    """The whole file proves nothing about sharding if the forced
    host-device-count flag silently failed to take: pin >1 device
    whenever the flag is present, SKIP (never pass) when it is not —
    a runner without the flag must show a skip in its report, not a
    green checkmark over single-device runs."""
    if "xla_force_host_platform_device_count" not in os.environ.get(
            "XLA_FLAGS", ""):
        pytest.skip("forced host device count flag absent — mesh tests "
                    "ran single-device")
    assert jax.device_count() > 1, (
        "XLA_FLAGS requested forced host devices but jax saw "
        f"{jax.device_count()} — the flag was set after jax "
        "initialized?")


def test_make_batch_mesh_shapes_and_bounds():
    mesh = make_batch_mesh(1)
    assert mesh.shape[BATCH_AXIS] == 1
    n = jax.device_count()
    assert make_batch_mesh(None).shape[BATCH_AXIS] == n
    with pytest.raises(ValueError):
        make_batch_mesh(n + 1)
    with pytest.raises(ValueError):
        make_batch_mesh(0)


def test_mesh_requires_divisible_universe():
    if jax.device_count() < 2:
        pytest.skip("needs >1 device")
    with pytest.raises(ValueError):
        MeshApplyTarget(0, 1023, A, mesh_devices=2)


def test_state_actually_sharded():
    if jax.device_count() < 2:
        pytest.skip("needs >1 device")
    mesh = MeshApplyTarget(0, E, A, mesh_devices=2)
    spec = mesh._state.present.sharding.spec
    assert tuple(spec) == (None, BATCH_AXIS)
    # lane fields shard; the clocks replicate
    assert tuple(mesh._state.vv.sharding.spec) in ((None, None), ())
    # two devices actually hold lane data
    assert len(mesh._state.present.sharding.device_set) == 2


# ---------------------------------------------------------------------------
# bitwise parity vs the single-device node
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("devices", [1, 2, 4, 8])
def test_ingest_bitwise_parity(devices):
    if jax.device_count() < devices:
        pytest.skip(f"needs {devices} devices")
    rng = np.random.default_rng(11)
    plain = Node(0, E, A)
    mesh = MeshApplyTarget(0, E, A, mesh_devices=devices)
    for add, dl, live in _random_batches(rng, 5):
        plain.ingest_batch(add, dl, live)
        mesh.ingest_batch(add, dl, live)
    _assert_states_equal(plain.state_slice(), mesh.state_slice(),
                         f"devices={devices}")


def test_wal_records_bitwise_identical(tmp_path):
    """Same batches ⇒ byte-identical WAL records: the mesh δ pull +
    host-side compact/dense ladder must encode exactly what the fused
    single-device path logs (replay compatibility is free once the
    bytes match)."""
    from go_crdt_playground_tpu.utils.wal import DeltaWal

    devices = min(jax.device_count(), 8)
    rng = np.random.default_rng(12)
    plain = Node(0, E, A, wal=DeltaWal(str(tmp_path / "wp")))
    mesh = MeshApplyTarget(0, E, A, mesh_devices=devices,
                           wal=DeltaWal(str(tmp_path / "wm")))
    for add, dl, live in _random_batches(rng, 4, add_p=0.02):
        plain.ingest_batch(add, dl, live)
        mesh.ingest_batch(add, dl, live)
    with plain._lock:
        rp = list(plain.wal.records())
    with mesh._lock:
        rm = list(mesh.wal.records())
    assert rp == rm and len(rm) == 4
    # one compiled dispatch per batch on the mesh path
    # (the recorder was None here; pin via a fresh recorded node)
    from go_crdt_playground_tpu.obs import Recorder

    rec = Recorder()
    m2 = MeshApplyTarget(0, E, A, mesh_devices=devices, recorder=rec,
                         wal=DeltaWal(str(tmp_path / "w2")))
    add, dl, live = next(_random_batches(rng, 1))
    m2.ingest_batch(add, dl, live)
    assert rec.snapshot()["counters"]["ingest.dispatches"] == 1


def test_digest_summary_parity_and_collective_kernel():
    """The collective digest read must be bitwise the single-device
    kernel's output — on the aligned path (shard-local folds) AND the
    misaligned fallback (E/devices not a multiple of the group)."""
    from go_crdt_playground_tpu.ops.digest import state_group_digests

    devices = min(jax.device_count(), 8)
    rng = np.random.default_rng(13)
    plain = Node(0, E, A)
    mesh = MeshApplyTarget(0, E, A, mesh_devices=devices)
    for add, dl, live in _random_batches(rng, 3):
        plain.ingest_batch(add, dl, live)
        mesh.ingest_batch(add, dl, live)
    sp, sm = plain.state_slice(), mesh.state_slice()
    for gs in (64, 128):
        assert np.array_equal(np.asarray(state_group_digests(sp, gs)),
                              np.asarray(mesh._digest_fn(sm, gs))), gs
    # misaligned: 8 devices over E=256 leaves 32-lane shards under a
    # 64-lane group — the fallback must still match bitwise
    if devices >= 2:
        p2, m2 = Node(0, 256, A), MeshApplyTarget(0, 256, A,
                                                  mesh_devices=devices)
        for add, dl, live in _random_batches(rng, 2, e=256, add_p=0.05):
            p2.ingest_batch(add, dl, live)
            m2.ingest_batch(add, dl, live)
        assert np.array_equal(
            np.asarray(state_group_digests(p2.state_slice(), 64)),
            np.asarray(m2._digest_fn(m2.state_slice(), 64)))
    # the summary frame itself round-trips through the digestsync codec
    from go_crdt_playground_tpu.net import digestsync

    body = mesh.digest_summary()
    actor, gs, vv, processed, digests = digestsync.decode_summary(
        body, E, A)
    assert actor == 0 and gs == 64
    assert np.array_equal(vv, np.asarray(sm.vv))


def test_slice_extract_and_apply_parity():
    """Handoff both halves: the mesh donor's lane-gather payload must
    be byte-identical to the dense single-device extraction, and a
    mesh recipient applying it must land bitwise where a plain node
    lands (including the re-pin to canonical placement)."""
    devices = min(jax.device_count(), 8)
    rng = np.random.default_rng(14)
    plain = Node(0, E, A)
    mesh = MeshApplyTarget(0, E, A, mesh_devices=devices)
    for add, dl, live in _random_batches(rng, 3, add_p=0.03):
        plain.ingest_batch(add, dl, live)
        mesh.ingest_batch(add, dl, live)
    mask = np.zeros(E, bool)
    mask[rng.choice(E, 100, replace=False)] = True
    body_plain = plain.extract_slice(mask)
    body_mesh = mesh.extract_slice(mask)
    assert body_plain == body_mesh
    # recipients (fresh, different actor) apply the same bytes
    rp = Node(1, E, A)
    rm = MeshApplyTarget(1, E, A, mesh_devices=devices)
    rp.apply_payload_body(body_plain)
    rm.apply_payload_body(body_mesh)
    _assert_states_equal(rp.state_slice(), rm.state_slice(), "recipient")
    assert tuple(rm._state.present.sharding.spec) == (None, BATCH_AXIS)


def test_sync_exchange_between_mesh_and_plain(tmp_path):
    """Anti-entropy runs UNCHANGED against the mesh target: a mesh
    node and a plain node converge over a real socket exchange in both
    the delta and digest regimes."""
    from go_crdt_playground_tpu.net import digestsync

    devices = min(jax.device_count(), 8)
    mesh = MeshApplyTarget(0, E, A, mesh_devices=devices)
    plain = Node(1, E, A)
    mesh.add(1, 2, 3)
    plain.add(500, 501)
    plain.delete(501)
    addr = plain.serve()
    try:
        mesh.sync_with(addr)
        mesh.sync_with(addr)  # second round: plain absorbed ours
        assert mesh.members().tolist() == [1, 2, 3, 500]
        assert plain.members().tolist() == [1, 2, 3, 500]
        # digest regime over the same listener
        mesh.add(7)
        stats = digestsync.sync_digest(mesh, addr)
        assert stats.groups_mismatched >= 1
        stats = digestsync.sync_digest(mesh, addr)
        assert stats.quiescent
    finally:
        plain.close()


# ---------------------------------------------------------------------------
# the 1-device degenerate case (satellite): frontend slice verbs ride
# the same code path the CRDT_SERVE_CRASH_ON_SLICE hooks arm
# ---------------------------------------------------------------------------


def test_single_device_frontend_degenerates_bitwise(tmp_path):
    """A ``--mesh-devices 1`` frontend must be observationally AND
    bitwise the plain frontend: same acks, same members, same durable
    state on disk, and the slice-transfer verbs (the path the
    ``CRDT_SERVE_CRASH_ON_SLICE`` kill hooks arm in the reshard soak)
    produce identical payload bytes."""
    from go_crdt_playground_tpu.serve.client import ServeClient
    from go_crdt_playground_tpu.serve.frontend import ServeFrontend

    fes = {}
    for name, mesh_devices in (("plain", None), ("mesh1", 1)):
        fe = ServeFrontend(256, A, actor=0,
                           durable_dir=str(tmp_path / name),
                           mesh_devices=mesh_devices, flush_ms=1.0)
        fes[name] = (fe, fe.serve())
    try:
        for name, (fe, addr) in fes.items():
            with ServeClient(addr) as c:
                c.add(3, 9, 27)
                c.add(81)
                c.delete(9)
                assert c.members()[0] == [3, 27, 81], name
        # the slice verbs (SLICE_PULL donor read) — hook-armed path
        elements = [3, 9, 27, 81, 100]
        pulls = {}
        for name, (fe, addr) in fes.items():
            with ServeClient(addr) as c:
                pulls[name] = c.slice_pull(elements)
        assert pulls["plain"] == pulls["mesh1"]
        # push the slice into both; states stay identical
        for name, (fe, addr) in fes.items():
            with ServeClient(addr) as c:
                c.slice_push(pulls["plain"])
        _assert_states_equal(fes["plain"][0].node.state_slice(),
                             fes["mesh1"][0].node.state_slice(),
                             "post-push")
    finally:
        for fe, _ in fes.values():
            fe.close()
    # durable restore of the mesh store with the PLAIN class (and vice
    # versa) lands on the same state: the disk format carries no
    # placement
    r_plain = Node.restore_durable(str(tmp_path / "mesh1"))
    r_mesh = MeshApplyTarget.restore_durable(
        str(tmp_path / "plain"), node_kwargs={"mesh_devices": 1})
    _assert_states_equal(r_plain.state_slice(), r_mesh.state_slice(),
                         "cross-restore")


def test_mesh_frontend_crash_on_slice_hook_subprocess(tmp_path):
    """The kill-mid-handoff hook against a REAL mesh worker: a
    ``serve --mesh-devices 2`` subprocess armed with
    ``CRDT_SERVE_CRASH_ON_SLICE=pull`` dies at the donor read without
    shipping state, and its durable restart serves every previously
    acked op — the degenerate-fleet version of the reshard soak's
    donor-death leg."""
    import subprocess
    import sys

    from go_crdt_playground_tpu.serve.client import ServeClient
    from go_crdt_playground_tpu.shard.fleet import _Proc, free_port

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    port = free_port()
    argv = [sys.executable, "-m", "go_crdt_playground_tpu", "serve",
            "--ingest", "--port", str(port), "--elements", "256",
            "--actors", "2", "--mesh-devices", "2",
            "--durable-dir", str(tmp_path / "state"),
            "--flush-ms", "1"]
    env = {"CRDT_SERVE_CRASH_ON_SLICE": "pull",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=2"}
    proc = _Proc(argv, cwd=repo, log_path=str(tmp_path / "w.log"),
                 env=env)
    try:
        addr = proc.await_address()
        with ServeClient(addr) as c:
            c.add(1, 2, 3)
            c.add(42)
        with pytest.raises((ConnectionError, OSError)):
            with ServeClient(addr) as c:
                c.slice_pull([1, 2])
        proc.proc.wait(timeout=30)
    finally:
        proc.close()
    # restart WITHOUT the hook: durable acks must all be there
    env2 = {"XLA_FLAGS": "--xla_force_host_platform_device_count=2"}
    proc2 = _Proc(argv, cwd=repo, log_path=str(tmp_path / "w2.log"),
                  env=env2, env_drop=("CRDT_SERVE_CRASH_ON_SLICE",))
    try:
        addr = proc2.await_address()
        with ServeClient(addr) as c:
            members, _ = c.members()
            assert members == [1, 2, 3, 42]
            # and the slice path now serves
            assert len(c.slice_pull([1, 2])) > 0
    finally:
        proc2.close()
