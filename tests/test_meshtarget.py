"""Device-mesh replica tier (parallel/meshtarget.py, DESIGN.md §20).

The correctness story is BITWISE: a ``MeshApplyTarget`` fed the same
batches as a plain single-device ``Node`` must produce identical state,
identical WAL record bytes, identical digest summaries, and identical
slice-transfer payloads — on every mesh size, including the 1-device
degenerate case.  The multi-device coverage is real: tests/conftest.py
forces ``--xla_force_host_platform_device_count=8`` before jax loads,
and ``test_mesh_tests_saw_multiple_devices`` pins that the flag
actually took (skip-not-pass when absent, so a stripped-down runner
can't silently demote every mesh test to single-device).
"""

import os

import numpy as np
import pytest

import jax

from go_crdt_playground_tpu.net.peer import Node
from go_crdt_playground_tpu.parallel.meshtarget import (BATCH_AXIS,
                                                        MeshApplyTarget,
                                                        make_batch_mesh)
from go_crdt_playground_tpu.parallel.meshtarget2d import (
    DP_AXIS, MP_AXIS, Mesh2DApplyTarget, parse_mesh_spec, plan_stripes)

E, A, B = 1024, 4, 8


def _random_batches(rng, n, e=E, add_p=0.01, del_p=0.005):
    for _ in range(n):
        yield (rng.random((B, e)) < add_p,
               rng.random((B, e)) < del_p,
               rng.random(B) < 0.85)


def _assert_states_equal(a, b, context=""):
    for name in a._fields:
        xa, xb = np.asarray(getattr(a, name)), np.asarray(getattr(b, name))
        assert np.array_equal(xa, xb), (context, name, xa, xb)


# ---------------------------------------------------------------------------
# the multi-device guarantee itself
# ---------------------------------------------------------------------------


def test_mesh_tests_saw_multiple_devices():
    """The whole file proves nothing about sharding if the forced
    host-device-count flag silently failed to take: pin >1 device
    whenever the flag is present, SKIP (never pass) when it is not —
    a runner without the flag must show a skip in its report, not a
    green checkmark over single-device runs."""
    if "xla_force_host_platform_device_count" not in os.environ.get(
            "XLA_FLAGS", ""):
        pytest.skip("forced host device count flag absent — mesh tests "
                    "ran single-device")
    assert jax.device_count() > 1, (
        "XLA_FLAGS requested forced host devices but jax saw "
        f"{jax.device_count()} — the flag was set after jax "
        "initialized?")


def test_make_batch_mesh_shapes_and_bounds():
    mesh = make_batch_mesh(1)
    assert mesh.shape[BATCH_AXIS] == 1
    n = jax.device_count()
    assert make_batch_mesh(None).shape[BATCH_AXIS] == n
    with pytest.raises(ValueError):
        make_batch_mesh(n + 1)
    with pytest.raises(ValueError):
        make_batch_mesh(0)


def test_mesh_requires_divisible_universe():
    if jax.device_count() < 2:
        pytest.skip("needs >1 device")
    with pytest.raises(ValueError):
        MeshApplyTarget(0, 1023, A, mesh_devices=2)


def test_state_actually_sharded():
    if jax.device_count() < 2:
        pytest.skip("needs >1 device")
    mesh = MeshApplyTarget(0, E, A, mesh_devices=2)
    spec = mesh._state.present.sharding.spec
    assert tuple(spec) == (None, BATCH_AXIS)
    # lane fields shard; the clocks replicate
    assert tuple(mesh._state.vv.sharding.spec) in ((None, None), ())
    # two devices actually hold lane data
    assert len(mesh._state.present.sharding.device_set) == 2


# ---------------------------------------------------------------------------
# bitwise parity vs the single-device node
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("devices", [1, 2, 4, 8])
def test_ingest_bitwise_parity(devices):
    if jax.device_count() < devices:
        pytest.skip(f"needs {devices} devices")
    rng = np.random.default_rng(11)
    plain = Node(0, E, A)
    mesh = MeshApplyTarget(0, E, A, mesh_devices=devices)
    for add, dl, live in _random_batches(rng, 5):
        plain.ingest_batch(add, dl, live)
        mesh.ingest_batch(add, dl, live)
    _assert_states_equal(plain.state_slice(), mesh.state_slice(),
                         f"devices={devices}")


def test_wal_records_bitwise_identical(tmp_path):
    """Same batches ⇒ byte-identical WAL records: the mesh δ pull +
    host-side compact/dense ladder must encode exactly what the fused
    single-device path logs (replay compatibility is free once the
    bytes match)."""
    from go_crdt_playground_tpu.utils.wal import DeltaWal

    devices = min(jax.device_count(), 8)
    rng = np.random.default_rng(12)
    plain = Node(0, E, A, wal=DeltaWal(str(tmp_path / "wp")))
    mesh = MeshApplyTarget(0, E, A, mesh_devices=devices,
                           wal=DeltaWal(str(tmp_path / "wm")))
    for add, dl, live in _random_batches(rng, 4, add_p=0.02):
        plain.ingest_batch(add, dl, live)
        mesh.ingest_batch(add, dl, live)
    with plain._lock:
        rp = list(plain.wal.records())
    with mesh._lock:
        rm = list(mesh.wal.records())
    assert rp == rm and len(rm) == 4
    # one compiled dispatch per batch on the mesh path
    # (the recorder was None here; pin via a fresh recorded node)
    from go_crdt_playground_tpu.obs import Recorder

    rec = Recorder()
    m2 = MeshApplyTarget(0, E, A, mesh_devices=devices, recorder=rec,
                         wal=DeltaWal(str(tmp_path / "w2")))
    add, dl, live = next(_random_batches(rng, 1))
    m2.ingest_batch(add, dl, live)
    assert rec.snapshot()["counters"]["ingest.dispatches"] == 1


def test_digest_summary_parity_and_collective_kernel():
    """The collective digest read must be bitwise the single-device
    kernel's output — on the aligned path (shard-local folds) AND the
    misaligned fallback (E/devices not a multiple of the group)."""
    from go_crdt_playground_tpu.ops.digest import state_group_digests

    devices = min(jax.device_count(), 8)
    rng = np.random.default_rng(13)
    plain = Node(0, E, A)
    mesh = MeshApplyTarget(0, E, A, mesh_devices=devices)
    for add, dl, live in _random_batches(rng, 3):
        plain.ingest_batch(add, dl, live)
        mesh.ingest_batch(add, dl, live)
    sp, sm = plain.state_slice(), mesh.state_slice()
    for gs in (64, 128):
        assert np.array_equal(np.asarray(state_group_digests(sp, gs)),
                              np.asarray(mesh._digest_fn(sm, gs))), gs
    # misaligned: 8 devices over E=256 leaves 32-lane shards under a
    # 64-lane group — the fallback must still match bitwise
    if devices >= 2:
        p2, m2 = Node(0, 256, A), MeshApplyTarget(0, 256, A,
                                                  mesh_devices=devices)
        for add, dl, live in _random_batches(rng, 2, e=256, add_p=0.05):
            p2.ingest_batch(add, dl, live)
            m2.ingest_batch(add, dl, live)
        assert np.array_equal(
            np.asarray(state_group_digests(p2.state_slice(), 64)),
            np.asarray(m2._digest_fn(m2.state_slice(), 64)))
    # the summary frame itself round-trips through the digestsync codec
    from go_crdt_playground_tpu.net import digestsync

    body = mesh.digest_summary()
    actor, gs, vv, processed, digests = digestsync.decode_summary(
        body, E, A)
    assert actor == 0 and gs == 64
    assert np.array_equal(vv, np.asarray(sm.vv))


def test_slice_extract_and_apply_parity():
    """Handoff both halves: the mesh donor's lane-gather payload must
    be byte-identical to the dense single-device extraction, and a
    mesh recipient applying it must land bitwise where a plain node
    lands (including the re-pin to canonical placement)."""
    devices = min(jax.device_count(), 8)
    rng = np.random.default_rng(14)
    plain = Node(0, E, A)
    mesh = MeshApplyTarget(0, E, A, mesh_devices=devices)
    for add, dl, live in _random_batches(rng, 3, add_p=0.03):
        plain.ingest_batch(add, dl, live)
        mesh.ingest_batch(add, dl, live)
    mask = np.zeros(E, bool)
    mask[rng.choice(E, 100, replace=False)] = True
    body_plain = plain.extract_slice(mask)
    body_mesh = mesh.extract_slice(mask)
    assert body_plain == body_mesh
    # recipients (fresh, different actor) apply the same bytes
    rp = Node(1, E, A)
    rm = MeshApplyTarget(1, E, A, mesh_devices=devices)
    rp.apply_payload_body(body_plain)
    rm.apply_payload_body(body_mesh)
    _assert_states_equal(rp.state_slice(), rm.state_slice(), "recipient")
    assert tuple(rm._state.present.sharding.spec) == (None, BATCH_AXIS)


def test_sync_exchange_between_mesh_and_plain(tmp_path):
    """Anti-entropy runs UNCHANGED against the mesh target: a mesh
    node and a plain node converge over a real socket exchange in both
    the delta and digest regimes."""
    from go_crdt_playground_tpu.net import digestsync

    devices = min(jax.device_count(), 8)
    mesh = MeshApplyTarget(0, E, A, mesh_devices=devices)
    plain = Node(1, E, A)
    mesh.add(1, 2, 3)
    plain.add(500, 501)
    plain.delete(501)
    addr = plain.serve()
    try:
        mesh.sync_with(addr)
        mesh.sync_with(addr)  # second round: plain absorbed ours
        assert mesh.members().tolist() == [1, 2, 3, 500]
        assert plain.members().tolist() == [1, 2, 3, 500]
        # digest regime over the same listener
        mesh.add(7)
        stats = digestsync.sync_digest(mesh, addr)
        assert stats.groups_mismatched >= 1
        stats = digestsync.sync_digest(mesh, addr)
        assert stats.quiescent
    finally:
        plain.close()


# ---------------------------------------------------------------------------
# the 1-device degenerate case (satellite): frontend slice verbs ride
# the same code path the CRDT_SERVE_CRASH_ON_SLICE hooks arm
# ---------------------------------------------------------------------------


def test_single_device_frontend_degenerates_bitwise(tmp_path):
    """A ``--mesh-devices 1`` frontend must be observationally AND
    bitwise the plain frontend: same acks, same members, same durable
    state on disk, and the slice-transfer verbs (the path the
    ``CRDT_SERVE_CRASH_ON_SLICE`` kill hooks arm in the reshard soak)
    produce identical payload bytes."""
    from go_crdt_playground_tpu.serve.client import ServeClient
    from go_crdt_playground_tpu.serve.frontend import ServeFrontend

    fes = {}
    for name, mesh_devices in (("plain", None), ("mesh1", 1)):
        fe = ServeFrontend(256, A, actor=0,
                           durable_dir=str(tmp_path / name),
                           mesh_devices=mesh_devices, flush_ms=1.0)
        fes[name] = (fe, fe.serve())
    try:
        for name, (fe, addr) in fes.items():
            with ServeClient(addr) as c:
                c.add(3, 9, 27)
                c.add(81)
                c.delete(9)
                assert c.members()[0] == [3, 27, 81], name
        # the slice verbs (SLICE_PULL donor read) — hook-armed path
        elements = [3, 9, 27, 81, 100]
        pulls = {}
        for name, (fe, addr) in fes.items():
            with ServeClient(addr) as c:
                pulls[name] = c.slice_pull(elements)
        assert pulls["plain"] == pulls["mesh1"]
        # push the slice into both; states stay identical
        for name, (fe, addr) in fes.items():
            with ServeClient(addr) as c:
                c.slice_push(pulls["plain"])
        _assert_states_equal(fes["plain"][0].node.state_slice(),
                             fes["mesh1"][0].node.state_slice(),
                             "post-push")
    finally:
        for fe, _ in fes.values():
            fe.close()
    # durable restore of the mesh store with the PLAIN class (and vice
    # versa) lands on the same state: the disk format carries no
    # placement
    r_plain = Node.restore_durable(str(tmp_path / "mesh1"))
    r_mesh = MeshApplyTarget.restore_durable(
        str(tmp_path / "plain"), node_kwargs={"mesh_devices": 1})
    _assert_states_equal(r_plain.state_slice(), r_mesh.state_slice(),
                         "cross-restore")


def _disjoint_batches(rng, n, e=E, bands=B, keys=4):
    """Key-disjoint op batches (row b draws only from its own lane
    band): the striping planner packs them into full stripes with
    zero cuts, so these pin the PARALLEL apply path specifically."""
    band = e // bands
    for _ in range(n):
        add = np.zeros((B, e), bool)
        dl = np.zeros((B, e), bool)
        for b in range(B):
            lanes = b * band + rng.choice(band, size=keys,
                                          replace=False)
            add[b, lanes[:keys - 1]] = True
            dl[b, lanes[keys - 1:]] = True
        yield add, dl, np.ones(B, bool)


# ---------------------------------------------------------------------------
# the 2-D dp×mp tier (parallel/meshtarget2d.py, DESIGN.md §24)
# ---------------------------------------------------------------------------


def test_parse_mesh_spec():
    assert parse_mesh_spec("8") == 8
    assert parse_mesh_spec(4) == 4
    assert parse_mesh_spec("2x4") == (2, 4)
    assert parse_mesh_spec((2, 2)) == (2, 2)
    assert parse_mesh_spec("1X4".lower()) == (1, 4)
    for bad in ("", "x", "2x", "x4", "0", "0x4", "2x0", "axb", "2x4x2",
                (0, 4), (2,)):
        with pytest.raises(ValueError):
            parse_mesh_spec(bad)


def test_plan_stripes_disjoint_and_cuts():
    """Planner invariants: key-disjoint batches pack into one chunk
    with balanced stripes; a key shared across rows chains them into
    ONE stripe; a row bridging two stripes cuts the chunk (order
    preserved: the cut row leads the next chunk)."""
    e = 64
    add = np.zeros((4, e), bool)
    for b in range(4):
        add[b, b * 16] = True
    dl = np.zeros((4, e), bool)
    live = np.ones(4, bool)
    plans, cuts = plan_stripes(add, dl, live, dp=2, cap=2)
    assert len(plans) == 1 and cuts == 0
    assert plans[0].stripes_used == 2 and plans[0].rows == 4
    # same key in rows 0 and 2: both must land in one stripe
    add2 = add.copy()
    add2[2] = add2[0]
    plans, cuts = plan_stripes(add2, dl, live, dp=2, cap=3)
    assert len(plans) == 1 and cuts == 0
    # rows 0 and 2 share lane 0: exactly one stripe holds lane 0 twice
    lane0 = plans[0].add[:, :, 0].sum(axis=1)
    assert sorted(lane0.tolist()) == [0, 2]
    # bridge: row 2 touches rows 0's and 1's keys -> cut
    add3 = np.zeros((3, e), bool)
    add3[0, 0] = True
    add3[1, 16] = True
    add3[2, 0] = add3[2, 16] = True
    plans, cuts = plan_stripes(add3, np.zeros((3, e), bool),
                               np.ones(3, bool), dp=2, cap=4)
    assert cuts == 1 and len(plans) == 2
    assert plans[0].rows == 2 and plans[1].rows == 1


@pytest.mark.parametrize("shape", ["1x2", "2x1", "2x2", "4x2", "2x4",
                                   "8x1", "1x8"])
def test_mesh2d_bitwise_parity(shape):
    """The tentpole pin: a striped 2-D target fed the same op log as a
    plain node lands BITWISE identical — every field, dots included —
    across degenerate and genuinely 2-D shapes, on random (conflicting)
    batches that exercise the cut path too."""
    dp, mp = (int(x) for x in shape.split("x"))
    if jax.device_count() < dp * mp:
        pytest.skip(f"needs {dp * mp} devices")
    rng = np.random.default_rng(21)
    plain = Node(0, E, A)
    mesh = Mesh2DApplyTarget(0, E, A, mesh_shape=shape)
    assert mesh.ingest_stripes == dp
    for add, dl, live in _random_batches(rng, 4, add_p=0.02):
        plain.ingest_batch(add, dl, live)
        mesh.ingest_batch(add, dl, live)
    # striped (disjoint) batches ride the parallel path specifically
    for add, dl, live in _disjoint_batches(rng, 2):
        plain.ingest_batch(add, dl, live)
        mesh.ingest_batch(add, dl, live)
    _assert_states_equal(plain.state_slice(), mesh.state_slice(),
                         f"shape={shape}")


def test_mesh2d_wal_byte_identity(tmp_path):
    """Disjoint batches ⇒ byte-identical WAL records across plain,
    1-D, and every 2-D shape (one record per batch, identical δ);
    conflicted batches may SPLIT records (one per chunk) but must
    REPLAY to the identical state — the durability semantics are the
    pinned surface, the byte split is the documented cost of a cut."""
    from go_crdt_playground_tpu.utils.wal import DeltaWal

    if jax.device_count() < 4:
        pytest.skip("needs 4 devices")
    rng = np.random.default_rng(22)
    nodes = {
        "plain": Node(0, E, A, wal=DeltaWal(str(tmp_path / "p"))),
        "1d": MeshApplyTarget(0, E, A, mesh_devices=4,
                              wal=DeltaWal(str(tmp_path / "m1"))),
        "2x2": Mesh2DApplyTarget(0, E, A, mesh_shape="2x2",
                                 wal=DeltaWal(str(tmp_path / "m22"))),
        "4x1": Mesh2DApplyTarget(0, E, A, mesh_shape="4x1",
                                 wal=DeltaWal(str(tmp_path / "m41"))),
    }
    for add, dl, live in _disjoint_batches(rng, 3):
        for n in nodes.values():
            n.ingest_batch(add, dl, live)
    recs = {}
    for name, n in nodes.items():
        with n._lock:
            recs[name] = list(n.wal.records())
    for name in nodes:
        assert recs[name] == recs["plain"], name
    assert len(recs["plain"]) == 3
    # conflicted batch: records may split, replay must converge
    add = np.zeros((B, E), bool)
    add[:, 5] = True  # every row touches lane 5: one stripe chain
    add[0, 100] = add[3, 200] = True
    for n in nodes.values():
        n.ingest_batch(add, np.zeros((B, E), bool), np.ones(B, bool))
    ref = nodes["plain"].state_slice()
    for name, n in nodes.items():
        _assert_states_equal(ref, n.state_slice(), f"post-conflict {name}")
    # replay each WAL into a fresh plain node: identical state again
    wal_dirs = {"plain": "p", "1d": "m1", "2x2": "m22", "4x1": "m41"}
    for name, n in nodes.items():
        with n._lock:
            n.wal.close()
        fresh = Node(0, E, A)
        replayed = fresh.replay_wal(
            DeltaWal(str(tmp_path / wal_dirs[name])))
        assert replayed["bad"] == 0 and replayed["future"] == 0
        _assert_states_equal(ref, fresh.state_slice(),
                             f"replay {name}")


def test_mesh2d_sharding_layout():
    """Lane fields shard trailing E over mp and REPLICATE over dp; the
    clocks replicate everywhere — the §24 layout table."""
    if jax.device_count() < 4:
        pytest.skip("needs 4 devices")
    mesh = Mesh2DApplyTarget(0, E, A, mesh_shape="2x2")
    spec = tuple(mesh._state.present.sharding.spec)
    assert spec == (None, MP_AXIS)
    assert mesh._mesh.shape[DP_AXIS] == 2
    assert mesh._mesh.shape[MP_AXIS] == 2
    assert tuple(mesh._state.vv.sharding.spec) in ((None, None), ())
    assert len(mesh._state.present.sharding.device_set) == 4
    # every digest/summary/slice read sees the joined replica: the
    # state is ONE logical array (converged in-dispatch), so reads
    # need no dp reduce — pin via digest parity with a plain node
    from go_crdt_playground_tpu.net import digestsync

    plain = Node(0, E, A)
    rng = np.random.default_rng(23)
    for add, dl, live in _disjoint_batches(rng, 2):
        plain.ingest_batch(add, dl, live)
        mesh.ingest_batch(add, dl, live)
    assert digestsync.node_summary(mesh) == digestsync.node_summary(plain)


def test_mesh2d_slice_and_cross_restore(tmp_path):
    """Handoff + durability across node classes: slice payloads are
    byte-identical, and a 2-D store restores with the plain/1-D class
    (and vice versa) — the disk format carries no placement."""
    if jax.device_count() < 4:
        pytest.skip("needs 4 devices")
    from go_crdt_playground_tpu.utils.wal import DeltaWal

    rng = np.random.default_rng(24)
    dirs = {name: tmp_path / name for name in ("plain", "2x2")}
    nodes = {
        "plain": Node(0, E, A, wal=DeltaWal(str(dirs["plain"] / "wal"))),
        "2x2": Mesh2DApplyTarget(0, E, A, mesh_shape=(2, 2),
                                 wal=DeltaWal(str(dirs["2x2"] / "wal"))),
    }
    for add, dl, live in _disjoint_batches(rng, 3):
        for n in nodes.values():
            n.ingest_batch(add, dl, live)
    mask = np.zeros(E, bool)
    mask[rng.choice(E, 64, replace=False)] = True
    assert nodes["plain"].extract_slice(mask) == \
        nodes["2x2"].extract_slice(mask)
    for name, n in nodes.items():
        from go_crdt_playground_tpu.utils.checkpoint import \
            CheckpointStore

        n.save_durable(CheckpointStore(str(dirs[name])))
        with n._lock:
            n.wal.close()
    # cross-class restore: 2-D store with the plain class, plain store
    # with the 2-D class (restore_durable node_kwargs plumbing)
    r_plain = Node.restore_durable(str(dirs["2x2"]))
    r_mesh = Mesh2DApplyTarget.restore_durable(
        str(dirs["plain"]), node_kwargs={"mesh_shape": "2x2"})
    _assert_states_equal(r_plain.state_slice(), r_mesh.state_slice(),
                         "cross-restore")
    assert tuple(r_mesh._state.present.sharding.spec) == (None, MP_AXIS)
    # and the restored 2-D node keeps serving striped batches bitwise
    rng2 = np.random.default_rng(25)
    add, dl, live = next(_disjoint_batches(rng2, 1))
    r_plain.ingest_batch(add, dl, live)
    r_mesh.ingest_batch(add, dl, live)
    _assert_states_equal(r_plain.state_slice(), r_mesh.state_slice(),
                         "post-restore ingest")


def test_mesh2d_requires_v2_semantics():
    with pytest.raises(ValueError):
        Mesh2DApplyTarget(0, E, A, mesh_shape="1x1",
                          delta_semantics="reference")


def test_mesh2d_frontend_stripe_width(tmp_path):
    """The serve seam: a 2-D frontend's batcher widens its drain
    watermark to dp × max_batch (the throughput axis), acks ride the
    same durable group commit, and QUERY sees the joined replica."""
    from go_crdt_playground_tpu.serve.client import ServeClient
    from go_crdt_playground_tpu.serve.frontend import ServeFrontend

    if jax.device_count() < 4:
        pytest.skip("needs 4 devices")
    fe = ServeFrontend(256, A, actor=0,
                       durable_dir=str(tmp_path / "s"),
                       mesh_devices="2x2", flush_ms=1.0, max_batch=8)
    assert fe.batcher.width == 16
    addr = fe.serve()
    try:
        with ServeClient(addr) as c:
            for e in range(0, 64, 2):
                c.add(e)
            c.delete(4)
            members, _ = c.members()
            assert members == sorted(set(range(0, 64, 2)) - {4})
    finally:
        fe.close()
    restored = Node.restore_durable(str(tmp_path / "s"))
    assert np.nonzero(np.asarray(
        restored.state_slice().present))[0].tolist() == \
        sorted(set(range(0, 64, 2)) - {4})


def test_mesh_frontend_crash_on_slice_hook_subprocess(tmp_path):
    """The kill-mid-handoff hook against a REAL mesh worker: a
    ``serve --mesh-devices 2`` subprocess armed with
    ``CRDT_SERVE_CRASH_ON_SLICE=pull`` dies at the donor read without
    shipping state, and its durable restart serves every previously
    acked op — the degenerate-fleet version of the reshard soak's
    donor-death leg."""
    import subprocess
    import sys

    from go_crdt_playground_tpu.serve.client import ServeClient
    from go_crdt_playground_tpu.shard.fleet import _Proc, free_port

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    port = free_port()
    argv = [sys.executable, "-m", "go_crdt_playground_tpu", "serve",
            "--ingest", "--port", str(port), "--elements", "256",
            "--actors", "2", "--mesh-devices", "2",
            "--durable-dir", str(tmp_path / "state"),
            "--flush-ms", "1"]
    env = {"CRDT_SERVE_CRASH_ON_SLICE": "pull",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=2"}
    proc = _Proc(argv, cwd=repo, log_path=str(tmp_path / "w.log"),
                 env=env)
    try:
        addr = proc.await_address()
        with ServeClient(addr) as c:
            c.add(1, 2, 3)
            c.add(42)
        with pytest.raises((ConnectionError, OSError)):
            with ServeClient(addr) as c:
                c.slice_pull([1, 2])
        proc.proc.wait(timeout=30)
    finally:
        proc.close()
    # restart WITHOUT the hook: durable acks must all be there
    env2 = {"XLA_FLAGS": "--xla_force_host_platform_device_count=2"}
    proc2 = _Proc(argv, cwd=repo, log_path=str(tmp_path / "w2.log"),
                  env=env2, env_drop=("CRDT_SERVE_CRASH_ON_SLICE",))
    try:
        addr = proc2.await_address()
        with ServeClient(addr) as c:
            members, _ = c.members()
            assert members == [1, 2, 3, 42]
            # and the slice path now serves
            assert len(c.slice_pull([1, 2])) > 0
    finally:
        proc2.close()
