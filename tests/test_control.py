"""Fleet-autopilot tests (control/, DESIGN.md §21): policy determinism
and safety (same trace ⇒ same decisions, hysteresis beats flapping,
abort ⇒ cooldown not retry-storm), signal windowing, standby-pool
bookkeeping, actuator failure ladder, and controller-restart
resumption from the router's persisted committed ring — the loop
itself in-process (the subprocess fleet is the slow-marked autopilot
soak's job)."""

import os
import time

import pytest

from go_crdt_playground_tpu.control import (AutopilotPolicy,
                                            FleetAutopilot, FleetSignals,
                                            PolicyConfig, ReshardActuator,
                                            StandbyPool)
from go_crdt_playground_tpu.control.policy import (ACTION_HOLD,
                                                   ACTION_MERGE,
                                                   ACTION_SPLIT,
                                                   OUTCOME_ABORTED,
                                                   OUTCOME_COMMITTED)
from go_crdt_playground_tpu.control.signals import FleetView, ShardSignals


# ---------------------------------------------------------------------------
# synthetic views (the policy never sees a socket)
# ---------------------------------------------------------------------------


def _view(t, p99s, *, queue=None, op_rate=50.0, shards=None,
          reachable=None, fenced=0, generation=0):
    shards = shards if shards is not None else [
        f"s{i}" for i in range(len(p99s))]
    per = {}
    for i, sid in enumerate(shards):
        per[sid] = ShardSignals(
            sid=sid,
            reachable=True if reachable is None else reachable[i],
            op_rate=op_rate, acked_rate=op_rate, shed_rate=0.0,
            queue_depth=0.0 if queue is None else queue[i],
            p99_s=p99s[i])
    return FleetView(t=t, generation=generation, digest="d",
                     shards=tuple(shards), fenced=fenced, load_stats={},
                     per_shard=per)


CFG = PolicyConfig(p99_budget_s=0.2, queue_watermark=10.0,
                   hot_windows=3, cold_windows=4, cooldown_s=5.0,
                   abort_cooldown_s=12.0, min_shards=2, max_shards=4,
                   cold_rate_per_shard=100.0)


def _trace_hot(n, hot_from=1):
    """s0 burns its p99 budget from view ``hot_from`` on."""
    return [_view(float(t), [0.5 if t >= hot_from else 0.05, 0.05])
            for t in range(n)]


def test_policy_same_trace_same_decisions():
    """Determinism: the decision sequence is a pure function of
    (config, seed, trace)."""
    trace = _trace_hot(8)
    pa, pb = AutopilotPolicy(CFG, seed=7), AutopilotPolicy(CFG, seed=7)
    a = [pa.decide(v).action for v in trace]
    b = [pb.decide(v).action for v in trace]
    assert a == b
    # and the shape is the banded one: holds until the streak fills,
    # then a split (the controller would actuate + cool down here;
    # without feedback the policy keeps asserting the same verdict)
    assert a[:3] == [ACTION_HOLD] * 3
    assert a[3] == ACTION_SPLIT


def test_policy_split_names_trigger_and_signals():
    pol = AutopilotPolicy(CFG)
    d = None
    for v in _trace_hot(6):
        d = pol.decide(v)
        if d.action == ACTION_SPLIT:
            break
    assert d is not None and d.action == ACTION_SPLIT
    assert d.hot_sid == "s0"
    rec = d.to_record()
    assert rec["signals"]["per_shard"]["s0"]["p99_ms"] == 500.0
    assert rec["reason"]


def test_policy_oscillation_never_splits():
    """The hysteresis half: a load flapping across the budget every
    other window never accumulates ``hot_windows`` consecutive hot
    samples, so it never fires."""
    pol = AutopilotPolicy(CFG)
    for t in range(40):
        hot = t % 2 == 0
        d = pol.decide(_view(float(t), [0.5 if hot else 0.05, 0.05]))
        assert d.action == ACTION_HOLD, (t, d)


def test_policy_abort_cooldown_not_retry_storm():
    """After an abort the policy HOLDS for abort_cooldown_s even under
    a sustained burn, then (burn persisting) decides exactly once
    more — never a tight retry loop."""
    pol = AutopilotPolicy(CFG)
    t = 0.0
    d = None
    while True:
        d = pol.decide(_view(t, [0.5, 0.05]))
        if d.action == ACTION_SPLIT:
            break
        t += 1.0
    pol.note_outcome(ACTION_SPLIT, OUTCOME_ABORTED, t)
    fired = []
    for dt in range(1, 20):
        d = pol.decide(_view(t + dt, [0.5, 0.05]))
        if d.action != ACTION_HOLD:
            fired.append((dt, d.action))
    # nothing fires inside the 12s abort cooldown; the streak keeps
    # accumulating through it by design (decide advances streaks on
    # every call), so a burn that persists refires on the FIRST view
    # at/past the window's edge — and not one view sooner
    assert fired, "burn persisted past cooldown but never refired"
    assert fired[0][0] == 12, fired
    assert all(dt >= 12 for dt, _ in fired)


def test_policy_commit_cooldown_shorter_than_abort():
    pol = AutopilotPolicy(CFG)
    pol.note_outcome(ACTION_SPLIT, OUTCOME_COMMITTED, 0.0)
    assert pol.decide(_view(4.9, [0.5, 0.05])).action == ACTION_HOLD
    pol2 = AutopilotPolicy(CFG)
    pol2.note_outcome(ACTION_SPLIT, OUTCOME_ABORTED, 0.0)
    # same instant relative to the two cooldowns: commit's has expired
    # (streaks still must refill), abort's has not
    d2 = pol2.decide(_view(5.1, [0.5, 0.05]))
    assert "cooldown" in d2.reason


def test_policy_cold_merge_and_min_shards():
    cold_cfg = PolicyConfig(p99_budget_s=0.2, queue_watermark=10.0,
                            hot_windows=3, cold_windows=3,
                            min_shards=2, max_shards=4,
                            cold_rate_per_shard=100.0)
    pol = AutopilotPolicy(cold_cfg)
    # 3 shards, idle: offered 30 ops/s total < 100 * 2 ⇒ cold
    acts = [pol.decide(_view(float(t), [0.01] * 3, op_rate=10.0)).action
            for t in range(5)]
    assert acts[:2] == [ACTION_HOLD] * 2
    assert ACTION_MERGE in acts
    # at min_shards the same trace only holds
    pol2 = AutopilotPolicy(cold_cfg)
    acts2 = [pol2.decide(_view(float(t), [0.01] * 2,
                               op_rate=10.0)).action
             for t in range(8)]
    assert acts2 == [ACTION_HOLD] * 8


def test_policy_cold_withheld_while_shard_dark():
    """An unreachable shard is 'no evidence', never 'cold': no merge
    may fire while part of the fleet is dark."""
    pol = AutopilotPolicy(CFG)
    for t in range(20):
        d = pol.decide(_view(float(t), [0.01, None, 0.01], op_rate=1.0,
                             reachable=[True, False, True]))
        assert d.action == ACTION_HOLD


def test_policy_max_shards_and_fence_hold():
    pol = AutopilotPolicy(CFG)
    for t in range(6):
        d = pol.decide(_view(float(t), [0.5] * 4))
    assert d.action == ACTION_HOLD and "max_shards" in d.reason
    pol2 = AutopilotPolicy(CFG)
    for t in range(6):
        d = pol2.decide(_view(float(t), [0.5, 0.05], fenced=7))
    assert d.action == ACTION_HOLD and "fenced" in d.reason


# ---------------------------------------------------------------------------
# signals: poll-to-poll windowing
# ---------------------------------------------------------------------------


def _stats(acked, buckets, *, queue=2.0, shed=0, rate=50.0,
           shards=("s0",), dark=()):
    shard_snaps = {}
    for sid in shards:
        if sid in dark:
            shard_snaps[sid] = None
            continue
        shard_snaps[sid] = {
            "counters": {"serve.ops.acked": acked,
                         "serve.shed.overload": shed},
            "gauges": {"serve.queue.depth": queue},
            "observations": {"serve.ingest_latency_s":
                             {"buckets": list(buckets)}}}
    return {"ring": {"generation": 3, "digest": "abc",
                     "shards": list(shards), "fenced": 0,
                     "load_stats": {"loads": [10] * len(shards)}},
            "shards": shard_snaps,
            "autopilot": {"op_rates": {sid: rate for sid in shards}}}


def test_signals_windowing():
    fs = FleetSignals()
    b0 = [0] * 64
    b1 = [0] * 64
    b1[30] = 100  # all this window's samples in one low bucket
    v1 = fs.ingest(_stats(100, b0), 10.0)
    assert v1.per_shard["s0"].p99_s is None  # first poll: no window
    v2 = fs.ingest(_stats(250, b1, shed=30), 13.0)
    s = v2.per_shard["s0"]
    assert s.acked_rate == pytest.approx(50.0)
    assert s.shed_rate == pytest.approx(10.0)
    # bucket 30's nominal upper bound: 1e-6 · √2^30 ≈ 33ms
    assert s.p99_s is not None and 0.01 < s.p99_s < 0.05
    assert s.op_rate == 50.0
    assert v2.generation == 3 and v2.load_stats["loads"] == [10]


def test_signals_counter_regression_reads_zero_not_negative():
    """A shard restart resets its counters; the window across the
    restart must read as no-evidence, never negative rates."""
    fs = FleetSignals()
    fs.ingest(_stats(1000, [0] * 64), 0.0)
    v = fs.ingest(_stats(50, [0] * 64), 1.0)
    assert v.per_shard["s0"].acked_rate == 0.0


def test_signals_unreachable_drops_window():
    fs = FleetSignals()
    fs.ingest(_stats(100, [0] * 64), 0.0)
    v = fs.ingest(_stats(100, [0] * 64, dark=("s0",)), 1.0)
    assert not v.per_shard["s0"].reachable
    assert v.per_shard["s0"].p99_s is None
    # back up: the first reachable poll rebuilds the baseline instead
    # of diffing across the outage
    v = fs.ingest(_stats(5, [0] * 64), 2.0)
    assert v.per_shard["s0"].reachable
    assert v.per_shard["s0"].acked_rate == 0.0


def test_view_imbalance():
    per = {
        "s0": ShardSignals("s0", True, 90.0, 0, 0, 0, None),
        "s1": ShardSignals("s1", True, 10.0, 0, 0, 0, None),
    }
    v = FleetView(0.0, 0, "d", ("s0", "s1"), 0, {}, per)
    assert v.imbalance() == pytest.approx(1.8)


# ---------------------------------------------------------------------------
# standby pool
# ---------------------------------------------------------------------------


def test_pool_roster_order_and_lifo_drain():
    pool = StandbyPool([("a", ("h", 1)), ("b", ("h", 2)),
                        ("c", ("h", 3))])
    assert pool.next_join()[0] == "a"
    pool.note_joined("a")
    pool.note_joined("b")
    assert pool.next_join()[0] == "c"
    assert pool.next_leave() == "b"  # LIFO: drain the newest first
    pool.note_left("b")
    assert pool.next_leave() == "a"


def test_pool_adopt_from_ring():
    pool = StandbyPool([("a", ("h", 1)), ("b", ("h", 2))])
    adopted = pool.adopt(["s0", "s1", "b"])
    assert adopted == ["b"] and pool.deployed == ["b"]
    assert pool.next_join()[0] == "a"
    with pytest.raises(ValueError):
        StandbyPool([("a", ("h", 1)), ("a", ("h", 2))])


# ---------------------------------------------------------------------------
# the loop against a real in-process fleet
# ---------------------------------------------------------------------------

E, A = 64, 5


@pytest.fixture()
def fleet(tmp_path):
    from go_crdt_playground_tpu.serve import ServeFrontend
    from go_crdt_playground_tpu.shard.router import ShardRouter

    fes = [ServeFrontend(E, A, actor=i,
                         durable_dir=str(tmp_path / f"s{i}"),
                         max_batch=8, flush_ms=1.0, queue_depth=32)
           for i in range(3)]
    addrs = {f"s{i}": fe.serve() for i, fe in enumerate(fes)}
    router = ShardRouter({k: v for k, v in addrs.items() if k != "s2"},
                         E, seed=5,
                         state_dir=str(tmp_path / "router-state"))
    raddr = router.serve()
    yield {"addrs": addrs, "router": router, "raddr": raddr,
           "tmp": tmp_path}
    router.close()
    for fe in fes:
        fe.close()


def _pilot(fleet, *, log_name="decisions.jsonl", **cfg_kw):
    cfg = PolicyConfig(**{**dict(queue_watermark=0.0, hot_windows=2,
                                 cooldown_s=2.0, max_shards=4), **cfg_kw})
    return FleetAutopilot(
        fleet["raddr"], [("s2", fleet["addrs"]["s2"])], config=cfg,
        poll_interval_s=30.0,  # cycles are test-driven via run_cycle
        decision_log=str(fleet["tmp"] / log_name), seed=3)


def test_controller_split_then_restart_resumes(fleet):
    """The loop end to end: a burn (queue_watermark=0 makes every view
    hot) splits the hot keyspace onto the standby via a REAL handoff;
    a NEW controller then resumes from the router's persisted
    committed ring — the standby reads as deployed, no double-join."""
    from go_crdt_playground_tpu.control.controller import \
        read_decision_log
    from go_crdt_playground_tpu.serve.client import ServeClient

    pilot = _pilot(fleet)
    resumed = pilot.start()
    assert resumed["generation"] == 0
    assert resumed["deployed_adopted"] == []
    try:
        deadline = time.monotonic() + 60.0
        while (pilot.pool.deployed != ["s2"]
               and time.monotonic() < deadline):
            pilot.run_cycle()
            time.sleep(0.05)
        assert pilot.pool.deployed == ["s2"]
    finally:
        pilot.stop()
    with ServeClient(fleet["raddr"]) as c:
        snap = c.stats()
    assert "s2" in snap["ring"]["shards"]
    assert snap["ring"]["generation"] == 1
    # the STATS surface the controller read: load_stats + op_rates
    assert len(snap["ring"]["load_stats"]["loads"]) == 3
    assert "op_rates" in snap["autopilot"]

    # the decision log holds the split decision WITH its triggering
    # signals and the committed outcome
    recs = read_decision_log(str(fleet["tmp"] / "decisions.jsonl"))
    assert recs[0]["record"] == "resume"
    splits = [r for r in recs if r["record"] == "decision"
              and r["action"] == ACTION_SPLIT]
    assert splits and splits[0]["signals"]["per_shard"]
    outs = [r for r in recs if r["record"] == "outcome"]
    assert outs and outs[0]["outcome"] == "committed"
    assert outs[0]["sid"] == "s2"

    # controller restart: the router's committed ring is the truth
    pilot2 = _pilot(fleet, log_name="d2.jsonl")
    resumed2 = pilot2.start()
    try:
        assert resumed2["generation"] == 1
        assert resumed2["deployed_adopted"] == ["s2"]
        # with the pool exhausted, a further burn skips (logged +
        # cooled), never re-joins the deployed standby
        for _ in range(4):
            pilot2.run_cycle()
    finally:
        pilot2.stop()
    recs2 = read_decision_log(str(fleet["tmp"] / "d2.jsonl"))
    joins = [r for r in recs2 if r["record"] == "outcome"
             and r.get("action") == ACTION_SPLIT
             and r.get("outcome") == "committed"]
    assert joins == []
    skips = [r for r in recs2 if r["record"] == "outcome"
             and r.get("outcome") == "skipped"]
    assert skips, recs2


def test_actuator_typed_abort_no_retry(fleet):
    """Joining a sid already in the ring is a deterministic typed
    abort: ONE attempt, outcome 'aborted', old ring untouched."""
    from go_crdt_playground_tpu.obs import Recorder
    from go_crdt_playground_tpu.serve.client import ServeClient

    rec = Recorder()
    act = ReshardActuator(fleet["raddr"], reshard_timeout_s=30.0,
                          recorder=rec, seed=1)
    out = act.join("s0", fleet["addrs"]["s0"])
    assert out.outcome == "aborted" and out.attempts == 1
    assert "already in the ring" in out.detail["reason"]
    assert rec.counter("control.actions.aborted") == 1
    assert rec.counter("control.actuator.retries") == 0
    with ServeClient(fleet["raddr"]) as c:
        assert c.stats()["ring"]["generation"] == 0


def test_actuator_unreachable_never_sends_without_baseline():
    """A dark router means no pre-action generation baseline, and
    without a baseline a transport-ambiguous verb could never be
    adjudicated — so the actuator retries the BASELINE read, then
    reports unreachable WITHOUT ever sending the verb."""
    from go_crdt_playground_tpu.obs import Recorder
    from go_crdt_playground_tpu.utils.backoff import BackoffPolicy

    rec = Recorder()
    act = ReshardActuator(
        ("127.0.0.1", 1), reshard_timeout_s=5.0, recorder=rec, seed=1,
        policy=BackoffPolicy(base_s=0.01, multiplier=2.0, cap_s=0.05,
                             jitter=0.1, max_retries=2))
    out = act.leave("s0")
    assert out.outcome == "unreachable"
    assert out.attempts == 0  # the verb was never sent
    assert "never sent" in out.detail["reason"]
    assert rec.counter("control.actions.unreachable") == 1
    assert rec.counter("control.actuator.retries") == 2


# ---------------------------------------------------------------------------
# decision-log reader
# ---------------------------------------------------------------------------


def test_read_decision_log_tolerates_torn_tail(tmp_path):
    from go_crdt_playground_tpu.control.controller import \
        read_decision_log

    p = str(tmp_path / "log.jsonl")
    with open(p, "w") as f:
        f.write('{"record": "resume", "seq": 0}\n')
        f.write('{"record": "decision", "seq": 1}\n')
        f.write('{"record": "outco')  # SIGKILL mid-append
    recs = read_decision_log(p)
    assert [r["seq"] for r in recs] == [0, 1]
    assert read_decision_log(str(tmp_path / "absent.jsonl")) == []
