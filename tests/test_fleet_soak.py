"""Host-fleet soak: N node PROCESSES gossiping over real TCP through
lossy proxies (VERDICT r4 item #7 — awset_test.go:16-17's exchange
model made real at fleet scale).

The parent runs one lossy TCP proxy per worker: a seeded 20% of proxied
connections are CUT after forwarding a random prefix (torn frames /
connection-closed mid-exchange — the socket-level face of a dropped
gossip round).  Workers additionally duplicate ~15% of exchanges and
reshuffle peer order per sweep (duplication + reordering).  Phase 2
sweeps every pair directly once the fleet is quiescent, after which
every replica must hold the identical global union — digest equality,
not just liveness.
"""

import json
import socket
import subprocess
import sys
import threading
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
N_WORKERS = 8
NUM_ELEMENTS = 64


class LossyProxy:
    """Forwards TCP connections to ``target_port``; a seeded fraction
    are cut after a random forwarded prefix (both directions pumped;
    the cut closes both ends abruptly)."""

    def __init__(self, target_port: int, seed: int, drop_rate: float = 0.2):
        self.target_port = target_port
        self.rng_lock = threading.Lock()
        self.rng = __import__("random").Random(seed)
        self.drop_rate = drop_rate
        self.total = 0
        self.dropped = 0
        self._closing = False
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(64)
        self.port = self.sock.getsockname()[1]
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            with self.rng_lock:
                self.total += 1
                cut = self.rng.random() < self.drop_rate
                cut_after = self.rng.randint(0, 40) if cut else None
                if cut:
                    self.dropped += 1
            threading.Thread(target=self._pump_pair, daemon=True,
                             args=(conn, cut_after)).start()

    def _pump_pair(self, conn: socket.socket, cut_after) -> None:
        try:
            upstream = socket.create_connection(
                ("127.0.0.1", self.target_port), timeout=5.0)
        except OSError:
            conn.close()
            return

        def pump(src, dst, budget):
            forwarded = 0
            try:
                while True:
                    take = 4096 if budget is None else min(
                        4096, budget - forwarded)
                    if take <= 0:
                        break
                    data = src.recv(take)
                    if not data:
                        break
                    dst.sendall(data)
                    forwarded += len(data)
            except OSError:
                pass
            finally:
                # abrupt close of BOTH ends: the peer sees a torn frame
                for s in (src, dst):
                    try:
                        s.close()
                    except OSError:
                        pass

        threading.Thread(target=pump, daemon=True,
                         args=(conn, upstream, cut_after)).start()
        pump(upstream, conn, cut_after)

    def close(self) -> None:
        self._closing = True
        try:
            self.sock.close()
        except OSError:
            pass


def _read_until(proc, prefix: str) -> str:
    while True:
        line = proc.stdout.readline()
        if not line:
            raise AssertionError(
                f"worker exited early: {proc.stderr.read()[-2000:]}")
        if line.startswith(prefix):
            return line.strip()


def test_fleet_converges_under_injected_loss():
    sys.path.insert(0, str(REPO))
    from __graft_entry__ import _scrubbed_cpu_env

    env = _scrubbed_cpu_env(1)
    workers = []
    proxies = []
    try:
        for i in range(N_WORKERS):
            workers.append(subprocess.Popen(
                [sys.executable, str(REPO / "tests" / "fleet_worker.py"),
                 str(i), str(N_WORKERS), str(NUM_ELEMENTS)],
                env=env, cwd=str(REPO), text=True,
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE))
        direct = [int(_read_until(w, "PORT").split()[1]) for w in workers]
        proxies = [LossyProxy(p, seed=7000 + j)
                   for j, p in enumerate(direct)]
        addrs = " ".join(str(p.port) for p in proxies) + " " + " ".join(
            str(p) for p in direct)
        for w in workers:
            w.stdin.write(f"ADDRS {addrs}\n")
            w.stdin.flush()
        for w in workers:
            _read_until(w, "PHASE1")
        # the loss injection must have actually fired: ~20% of ~4
        # sweeps x 7 peers x ~1.15 dials x 8 workers ~ 50 connections
        assert sum(p.dropped for p in proxies) >= 10
        assert sum(p.total for p in proxies) >= 100
        for w in workers:
            w.stdin.write("PHASE2\n")
            w.stdin.flush()
        for w in workers:
            _read_until(w, "PHASE2DONE")
        for w in workers:
            w.stdin.write("REPORT\n")
            w.stdin.flush()
        reports = [json.loads(_read_until(w, "{")) for w in workers]
        for w in workers:
            assert w.wait(timeout=30) == 0
    finally:
        for p in proxies:
            p.close()
        for w in workers:
            if w.poll() is None:
                w.kill()

    expected = sorted(e for i in range(N_WORKERS)
                      for e in range(i * 4, i * 4 + 4))
    lost = sum(r["lost"] for r in reports)
    assert lost >= 10, "proxy cuts must surface as lost exchanges"
    for i, r in enumerate(reports):
        assert r["members"] == expected, f"worker {i} diverged"
        assert r["vv"] == reports[0]["vv"], f"worker {i} VV diverged"
