"""ConnHost (serve/host.py): the listener/reader/conn-slot plumbing the
frontend and router used to hand-copy from each other, extracted so
accept-path fixes land once.  jax-free and cheap: pure socket plumbing.
"""

import socket
import threading
import time

import pytest

from go_crdt_playground_tpu.net import framing
from go_crdt_playground_tpu.serve.host import ConnHost


def _dial(addr, timeout=5.0):
    return socket.create_connection(addr, timeout=timeout)


def test_dispatch_roundtrip_and_unknown_frame_closes():
    def dispatch(session, msg_type, body):
        if msg_type == 99:
            session.send(100, body.upper())
            return True
        session.send(framing.MSG_ERROR, b"unknown verb")
        return False

    host = ConnHost(dispatch, thread_name="test-host")
    addr = host.listen()
    try:
        conn = _dial(addr)
        try:
            framing.send_frame(conn, 99, b"abc")
            t, b = framing.recv_frame(conn, timeout=5.0)
            assert (t, b) == (100, b"ABC")
            # an unknown frame ends the connection (the MSG_ERROR
            # reply is best-effort: the close may tear it off first)
            framing.send_frame(conn, 50, b"")
            with pytest.raises((framing.RemoteError,
                                framing.TruncatedFrame, OSError)):
                framing.recv_frame(conn, timeout=5.0)
        finally:
            conn.close()
    finally:
        host.stop_accepting()
        host.close_sessions(0.5)


def test_closed_listener_refuses_new_dials():
    """THE shared-host regression (pre-extraction, frontend.py and
    router.py each carried this fix by hand): a bare listener close
    does not wake the blocked accept loop on this kernel, and until it
    wakes the kernel keeps COMPLETING new dials into the backlog — so
    "stopped accepting" must mean refused-at-the-kernel, which only
    shutdown-before-close delivers."""
    host = ConnHost(lambda s, t, b: True, thread_name="test-host")
    addr = host.listen()
    live = _dial(addr)
    live.close()
    host.stop_accepting()
    # every new dial must now fail outright — never accepted-then-idle
    for _ in range(3):
        with pytest.raises(OSError):
            c = _dial(addr, timeout=2.0)
            c.close()  # unreachable; close if the dial wrongly landed
    host.close_sessions(0.5)


def test_connection_slot_cap_sheds_and_recovers():
    host = ConnHost(lambda s, t, b: True, thread_name="test-host",
                    max_conns=1)
    addr = host.listen()
    try:
        c1 = _dial(addr)
        time.sleep(0.1)  # let the accept loop take the only slot
        # second dial: TCP-accepted then immediately dropped by the gate
        c2 = _dial(addr)
        c2.settimeout(5.0)
        assert c2.recv(1) == b"", "shed dial was not closed"
        c2.close()
        c1.close()
        # the released slot admits again (reader teardown is async)
        deadline = time.monotonic() + 10.0
        admitted = False
        while time.monotonic() < deadline and not admitted:
            c3 = _dial(addr)
            c3.settimeout(0.3)
            try:
                c3.recv(1)
            except socket.timeout:
                admitted = True  # still open: the slot took us
            except OSError:
                time.sleep(0.05)
            finally:
                c3.close()
        assert admitted, "released slot never admitted a new dial"
    finally:
        host.stop_accepting()
        host.close_sessions(0.5)


def test_sessions_registry_and_shared_flush_window():
    """close_sessions drains under ONE shared deadline and empties the
    registry; readers observe their session closing."""
    stop = threading.Event()

    def dispatch(session, msg_type, body):
        session.send(msg_type, body)
        return not stop.is_set()

    host = ConnHost(dispatch, thread_name="test-host")
    addr = host.listen()
    conns = [_dial(addr) for _ in range(3)]
    for i, c in enumerate(conns):
        framing.send_frame(c, 99, b"x")
        framing.recv_frame(c, timeout=5.0)
    assert len(host.sessions()) == 3
    host.stop_accepting()
    t0 = time.monotonic()
    host.close_sessions(flush_timeout_s=1.0)
    assert time.monotonic() - t0 < 5.0, "flush was per-session, not shared"
    assert host.sessions() == []
    for c in conns:
        c.close()
