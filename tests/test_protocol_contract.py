"""Protocol-contract analyzer suite (DESIGN.md §15 W001-W004 + M001).

Two halves:

* **adversarial codec vectors** — the W003 harness run standalone over
  the full codec registry (roundtrip / truncation-at-every-boundary /
  garble / varint inflation), plus hand-crafted oversized and
  out-of-range vectors per body family, including the regression
  vectors for the true positive the harness found during development
  (``decode_members`` shipped without the uint32 range check and the
  count-exceeds-body allocation guard every sibling decoder carries);
* **seeded-injection tests** — each pass is fed a planted violation
  (deleted dispatch arm, stale ignore, lost fallthrough, bare literal
  reject code, asymmetric codec, untyped-error decoder, bare
  recv_frame, phantom metric, stale committed report) and must fire.
  A gate that cannot fail proves nothing.
"""

import json
import os

import numpy as np
import pytest

from go_crdt_playground_tpu.analysis import (codec_symmetry,
                                             metrics_contract,
                                             protocol_contract)
from go_crdt_playground_tpu.analysis.codec_symmetry import (CodecSpec,
                                                            build_codecs,
                                                            check_codec)
from go_crdt_playground_tpu.analysis.protocol_contract import \
    DispatcherSpec
from go_crdt_playground_tpu.net.framing import ProtocolError
from go_crdt_playground_tpu.serve import protocol

PKG = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "go_crdt_playground_tpu")


# ---------------------------------------------------------------------------
# W003 harness, standalone over the real registry
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("spec", build_codecs(), ids=lambda s: s.name)
def test_codec_contract(spec):
    """Per msg type: roundtrip identity, truncation at every boundary
    varint, seeded garble, and varint inflation — all typed."""
    rng = np.random.default_rng(1234)
    findings = check_codec(spec, rng, n_samples=3, n_garbles=12)
    assert not findings, [f.render() for f in findings]


def test_registry_covers_every_wire_module_codec():
    findings, stats = codec_symmetry.check_coverage(PKG, build_codecs())
    assert not findings, [f.render() for f in findings]
    assert stats["codec_functions"] >= 40


# ---------------------------------------------------------------------------
# Hand-crafted adversarial vectors (committed, seeded by construction)
# ---------------------------------------------------------------------------


def _varint(v: int) -> bytes:
    out = bytearray()
    while True:
        if v < 0x80:
            out.append(v)
            return bytes(out)
        out.append((v & 0x7F) | 0x80)
        v >>= 7


def test_members_vv_entry_over_uint32_is_typed():
    """REGRESSION (the W003 true positive): a 5-byte varint vv entry
    in a MEMBERS reply raised OverflowError THROUGH the client reader
    thread instead of the typed ProtocolError — decode_members was the
    one decoder without the range check."""
    body = (_varint(7)          # req_id
            + _varint(0)        # no members
            + _varint(1)        # one vv entry
            + _varint(1 << 32))  # > uint32
    with pytest.raises(ProtocolError):
        protocol.decode_members(body)


def test_members_count_beyond_body_is_typed_not_alloc():
    """A hostile vv count must fail BEFORE np.zeros ever sees it."""
    body = (_varint(7) + _varint(0)
            + _varint(1 << 40))  # vv count: ~10^12 entries, 3 bytes left
    with pytest.raises(ProtocolError):
        protocol.decode_members(body)
    # member count beyond body likewise
    body = _varint(7) + _varint(1 << 40)
    with pytest.raises(ProtocolError):
        protocol.decode_members(body)


def test_frontier_reply_oversized_count_is_typed():
    body = (_varint(7) + bytes([0])  # flags
            + _varint(1 << 40))      # array count beyond body
    with pytest.raises(ProtocolError):
        protocol.decode_frontier_reply(body)


def test_op_oversized_key_count_is_typed():
    body = (_varint(7) + bytes([protocol.OP_ADD]) + _varint(0)
            + _varint(1 << 40))  # k elements, none present
    with pytest.raises(ProtocolError):
        protocol.decode_op(body)


def test_lane_section_claims_more_lanes_than_universe():
    from go_crdt_playground_tpu.utils import wire

    E, A = 8, 2
    body = (_varint(E)
            + wire._encode_vv_py(np.zeros(A, np.uint32))
            + _varint(E + 1))  # lane section claims E+1 lanes
    with pytest.raises(ValueError):
        wire.decode_payload_lanes(body, E, A)


def test_lane_id_outside_universe_is_typed():
    from go_crdt_playground_tpu.utils import wire

    E, A = 8, 2
    body = (_varint(E)
            + wire._encode_vv_py(np.zeros(A, np.uint32))
            + _varint(1) + _varint(E) + _varint(0) + _varint(1)  # lane E
            + _varint(0))
    with pytest.raises(ValueError):
        wire.decode_payload_lanes(body, E, A)


def test_summary_digest_count_mismatch_is_typed():
    from go_crdt_playground_tpu.net import digestsync

    E, A, GS = 16, 4, 4
    good = digestsync.encode_summary(
        0, E, GS, np.zeros(A, np.uint32), np.zeros(A, np.uint32),
        np.zeros(4, np.uint32))
    digestsync.decode_summary(good, E, A)  # sanity
    bad = digestsync.encode_summary(
        0, E, GS, np.zeros(A, np.uint32), np.zeros(A, np.uint32),
        np.zeros(3, np.uint32))  # one group short
    with pytest.raises(ProtocolError):
        digestsync.decode_summary(bad, E, A)


# ---------------------------------------------------------------------------
# Seeded injections: every pass must be able to fire
# ---------------------------------------------------------------------------


def test_w003_detects_asymmetric_codec():
    """A codec whose decode drifted from its encode (drops a field)."""
    spec = CodecSpec(
        name="planted-asym",
        encode=lambda a, b: bytes([a, b]),
        decode=lambda body: (body[0], 0) if len(body) == 2
        else (_ for _ in ()).throw(ValueError("short")),
        gen=lambda rng: (int(rng.integers(1, 100)),
                         int(rng.integers(1, 100))),
        expected=lambda args: args,
        typed_errors=(ValueError,), covers=())
    rng = np.random.default_rng(0)
    findings = check_codec(spec, rng, n_samples=2, n_garbles=2)
    assert any("roundtrip mismatch" in f.message for f in findings)


def test_w003_detects_untyped_decoder_error():
    """A decoder raising IndexError on truncation (the reader-thread
    killer) is a finding, not a pass."""
    spec = CodecSpec(
        name="planted-untyped",
        encode=lambda v: _varint(v) + bytes(2),
        decode=lambda body: (body[0], body[1], body[2]),  # IndexError
        gen=lambda rng: (int(rng.integers(0, 50)),),
        expected=lambda args: None,
        typed_errors=(ValueError,), covers=(),
        compare=lambda got, want: True)
    rng = np.random.default_rng(0)
    findings = check_codec(spec, rng, n_samples=1, n_garbles=0)
    assert any("UNTYPED IndexError" in f.message for f in findings)


_PLANTED_DIALECT = '''\
from go_crdt_playground_tpu.net import framing

MSG_A = 1
MSG_B = 2
MSG_R = 3  # protocol-ignore: reply — planted reply frame


class D:
    def _dispatch(self, session, msg_type, body):
        if msg_type == MSG_A:
            return True
        session.send(framing.MSG_ERROR, b"?")
        return False

    def _read_loop(self):
        msg_type = 0
        if msg_type == MSG_R:
            return framing.ProtocolError
        return None
'''


def _plant(tmp_path, source):
    mod = tmp_path / "planted.py"
    mod.write_text(source)
    return str(tmp_path), "planted.py"


def _specs(rel):
    return (
        DispatcherSpec("planted", rel, "D._dispatch", (rel,),
                       "server", "MSG_ERROR"),
        DispatcherSpec("planted-client", rel, "D._read_loop", (rel,),
                       "client", "ProtocolError"),
    )


def test_w001_detects_deleted_dispatch_arm(tmp_path):
    root, rel = _plant(tmp_path, _PLANTED_DIALECT)
    findings, stats = protocol_contract.check_dispatchers(
        root, _specs(rel))
    holes = [f for f in findings if "no handler arm" in f.message]
    assert len(holes) == 1 and "MSG_B" in holes[0].symbol
    # the client spec is satisfied: MSG_R has a reply arm
    assert stats["dispatchers"]["planted-client"]["required"] == ["MSG_R"]


def test_w001_annotated_hole_is_clean(tmp_path):
    src = _PLANTED_DIALECT.replace(
        "        session.send(framing.MSG_ERROR",
        "        # protocol-ignore: MSG_B — planted exclusion\n"
        "        session.send(framing.MSG_ERROR")
    root, rel = _plant(tmp_path, src)
    findings, _ = protocol_contract.check_dispatchers(root, _specs(rel))
    assert not findings, [f.render() for f in findings]


def test_w001_stale_ignore_is_a_finding(tmp_path):
    src = _PLANTED_DIALECT.replace(
        "        if msg_type == MSG_A:",
        "        # protocol-ignore: MSG_A — planted stale ignore\n"
        "        if msg_type == MSG_A:")
    root, rel = _plant(tmp_path, src)
    findings, _ = protocol_contract.check_dispatchers(root, _specs(rel))
    assert any("stale protocol-ignore" in f.message for f in findings)


def test_w001_lost_fallthrough_is_a_finding(tmp_path):
    src = _PLANTED_DIALECT.replace(
        '        session.send(framing.MSG_ERROR, b"?")\n', "")
    src = src.replace("MSG_B = 2\n", "")  # isolate the fallthrough check
    root, rel = _plant(tmp_path, src)
    findings, _ = protocol_contract.check_dispatchers(
        root, _specs(rel)[:1])
    assert any("fallthrough" in f.message for f in findings)


def test_w001_reply_constant_needs_client_arm(tmp_path):
    src = _PLANTED_DIALECT.replace("        if msg_type == MSG_R:\n"
                                   "            return framing."
                                   "ProtocolError\n",
                                   "        del msg_type\n")
    src = src.replace("    def _read_loop(self):\n",
                      "    def _read_loop(self):\n"
                      "        err = framing.ProtocolError\n")
    root, rel = _plant(tmp_path, src)
    findings, _ = protocol_contract.check_dispatchers(
        root, _specs(rel)[1:])
    assert any("MSG_R" in (f.symbol or "") for f in findings)


def test_w002_registry_bijection_holds():
    findings, stats = protocol_contract.check_reject_registry()
    assert not findings, [f.render() for f in findings]
    assert stats["codes"] == stats["constants"] == \
        stats["exception_classes"] >= 6


def test_w002_detects_unregistered_reject_code(tmp_path):
    mod = tmp_path / "planted_reject.py"
    mod.write_text(
        "from go_crdt_playground_tpu.serve import protocol\n"
        "def f(session, req_id):\n"
        "    session.send(18, protocol.encode_reject(req_id, 99, 'x'))\n"
        "    session.send(18, protocol.encode_reject(\n"
        "        req_id, protocol.REJECT_BOGUS, 'y'))\n")
    findings, stats = protocol_contract.check_reject_call_sites(
        [str(mod)])
    msgs = [f.message for f in findings]
    assert any("bare literal" in m for m in msgs)
    assert any("REJECT_BOGUS" in m for m in msgs)
    assert stats["reject_sites"] == 2


def test_w004_detects_bare_recv_frame(tmp_path):
    mod = tmp_path / "planted_recv.py"
    mod.write_text(
        "from go_crdt_playground_tpu.net import framing\n"
        "def f(sock):\n"
        "    framing.recv_frame(sock)\n"                # bare: finding
        "    framing.recv_frame(sock, timeout=1.0)\n"   # bare: finding
        "    framing.recv_frame(sock, 1.0, 4096)\n"     # explicit
        "    framing.recv_frame(sock, max_body=4096)\n")  # explicit
    findings, stats = protocol_contract.check_frame_caps([str(mod)])
    assert len(findings) == 2 and stats["recv_frame_sites"] == 4


def test_w002_keyword_form_code_is_checked(tmp_path):
    """Review regression: a bare literal riding ``code=...`` keyword
    form must not slip past the call-site lint."""
    mod = tmp_path / "planted_kw.py"
    mod.write_text(
        "from go_crdt_playground_tpu.serve import protocol\n"
        "def f(req_id):\n"
        "    return protocol.encode_reject(req_id, code=99, "
        "reason='x')\n")
    findings, stats = protocol_contract.check_reject_call_sites(
        [str(mod)])
    assert stats["reject_sites"] == 1
    assert any("bare literal" in f.message for f in findings)


def test_w004_relative_import_is_not_exempt(tmp_path):
    """Review regression: ``from ..net import framing`` (relative) and
    the direct relative recv_frame import must still be attributed to
    the armored framing module."""
    mod = tmp_path / "planted_rel.py"
    mod.write_text(
        "from ..net import framing\n"
        "from .framing import recv_frame\n"
        "def f(sock):\n"
        "    framing.recv_frame(sock)\n"
        "    recv_frame(sock)\n")
    findings, stats = protocol_contract.check_frame_caps([str(mod)])
    assert len(findings) == 2 and stats["recv_frame_sites"] == 2


def test_w004_ignores_foreign_recv_frame(tmp_path):
    """bridge/service.py's own struct-framed recv_frame must not be
    misattributed to the armored framing one."""
    mod = tmp_path / "own_framing.py"
    mod.write_text(
        "def recv_frame(sock):\n"
        "    return 0, b''\n"
        "def f(sock):\n"
        "    recv_frame(sock)\n")
    findings, stats = protocol_contract.check_frame_caps([str(mod)])
    assert not findings and stats["recv_frame_sites"] == 0


def test_w004_package_has_no_bare_recv_frame():
    """The acceptance pin: every recv_frame call site in the package
    passes an explicit cap (serve client, peer exchange, digest
    exchange — the PR's found-and-fixed bare reads stay fixed)."""
    py_files = []
    for dirpath, _d, filenames in os.walk(PKG):
        if "__pycache__" in dirpath:
            continue
        py_files.extend(os.path.join(dirpath, fn) for fn in filenames
                        if fn.endswith(".py"))
    findings, stats = protocol_contract.check_frame_caps(py_files)
    assert not findings, [f.render() for f in findings]
    assert stats["recv_frame_sites"] >= 9


def test_m001_detects_phantom_metric(tmp_path):
    pkg = tmp_path / "pkg.py"
    pkg.write_text(
        "def f(recorder):\n"
        "    recorder.count('serve.real.metric')\n")
    tool = tmp_path / "planted_soak.py"
    tool.write_text(
        "def adjudicate(counters):\n"
        "    assert counters.get('serve.phantom.metric', 0) > 0\n"
        "    assert counters.get('serve.real.metric', 0) > 0\n")
    doc = tmp_path / "DESIGN.md"
    doc.write_text("`serve.real.metric` is documented.\n")
    findings, stats = metrics_contract.check(
        [str(pkg)], [str(tool)], [str(doc)])
    errs = [f for f in findings if f.severity == "error"]
    assert len(errs) == 1 and errs[0].symbol == "serve.phantom.metric"


def test_m001_fstring_pattern_covers_classified_reference(tmp_path):
    pkg = tmp_path / "pkg.py"
    pkg.write_text(
        "def f(recorder, cls):\n"
        "    recorder.count(f'sync.failures.{cls}')\n")
    tool = tmp_path / "planted_soak.py"
    tool.write_text("NAME = 'sync.failures.remote'\n")
    doc = tmp_path / "DESIGN.md"
    doc.write_text("`sync.failures.<class>` per failure class.\n")
    findings, _ = metrics_contract.check(
        [str(pkg)], [str(tool)], [str(doc)])
    assert not findings, [f.render() for f in findings]


def test_m001_undocumented_emission_is_a_warning(tmp_path):
    pkg = tmp_path / "pkg.py"
    pkg.write_text(
        "def f(recorder):\n"
        "    recorder.count('serve.undocumented.metric')\n")
    doc = tmp_path / "DESIGN.md"
    doc.write_text("nothing here\n")
    findings, _ = metrics_contract.check([str(pkg)], [], [str(doc)])
    assert len(findings) == 1
    assert findings[0].severity == "warning"
    assert findings[0].symbol == "serve.undocumented.metric"


def test_annotation_kinds_do_not_shadow_on_one_statement():
    """Review regression: a guarded-by above a statement plus a
    trailing protocol-ignore on the statement line must BOTH resolve —
    the kind-filtered lookup can't be shadowed by the other kind."""
    from go_crdt_playground_tpu.analysis.annotations import (
        KIND_GUARDED_BY, KIND_PROTOCOL_IGNORE, parse_annotations)

    src = ("class C:\n"
           "    def __init__(self):\n"
           "        # guarded-by: _lock\n"
           "        self.x = 1  # protocol-ignore: reply — planted\n")
    a = parse_annotations(src)
    g = a.on_lines(4, 4, KIND_GUARDED_BY)
    assert g is not None and g.arg == "_lock"
    p = a.on_lines(4, 4, KIND_PROTOCOL_IGNORE)
    assert p is not None and p.arg.startswith("reply")


def test_report_freshness_regeneration_run_is_clean(tmp_path):
    """Review regression: the documented F001 fix command (default
    --out == the committed path) must exit 0 on its FIRST run and
    write an artifact free of the stale-against-itself finding."""
    from go_crdt_playground_tpu.analysis.__main__ import main

    path = tmp_path / "ANALYSIS_REPORT.json"
    path.write_text(json.dumps({"passes": {"only": {}}}))  # stale
    rc = main(["--fast", "--skip-runtime", "--out", str(path),
               "--committed-report", str(path)])
    assert rc == 0
    fresh = json.loads(path.read_text())
    assert fresh["ok"] and fresh["n_findings"] == 0
    assert fresh["passes"]["report_freshness"]["stats"]["mode"] == \
        "regenerating"


def test_report_freshness_detects_stale_pass_list(tmp_path):
    from go_crdt_playground_tpu.analysis.__main__ import (
        REGISTERED_PASSES, check_report_freshness)
    from go_crdt_playground_tpu.analysis.report import Report

    stale = {"passes": {name: {} for name in REGISTERED_PASSES
                        if name != "codec_symmetry"}}
    path = tmp_path / "ANALYSIS_REPORT.json"
    path.write_text(json.dumps(stale))
    report = Report()
    check_report_freshness(report, str(path))
    assert report.errors() and "stale" in report.errors()[0].message

    fresh = {"passes": {name: {} for name in REGISTERED_PASSES}}
    path.write_text(json.dumps(fresh))
    report2 = Report()
    check_report_freshness(report2, str(path))
    assert not report2.errors()


# ---------------------------------------------------------------------------
# The real tree is clean (the acceptance criterion, test-speed slice)
# ---------------------------------------------------------------------------


def test_router_links_scale_reply_cap_with_universe():
    """Review regression: the router's downstream clients must size
    their reply cap from E — a donor SLICE_STATE reply scales with the
    universe, and the flat 64MB client default would make a
    large-universe reshard permanently impossible."""
    from go_crdt_playground_tpu.serve.client import ServeClient
    from go_crdt_playground_tpu.shard.router import ShardRouter

    E = 16 << 20  # a universe whose slice cap exceeds the 64MB floor
    r = ShardRouter({"s0": ("127.0.0.1", 1)}, E)
    try:
        link = r.link("s0")
        assert link.max_reply_body == 16 * E + 4096
        assert link.max_reply_body > ServeClient.MAX_REPLY_BODY
        small = ShardRouter({"s0": ("127.0.0.1", 1)}, 64)
        try:
            assert (small.link("s0").max_reply_body
                    == ServeClient.MAX_REPLY_BODY)
        finally:
            small.close()
    finally:
        r.close()


def test_real_dispatchers_are_exhaustive():
    findings, stats = protocol_contract.check_dispatchers(PKG)
    assert not findings, [f.render() for f in findings]
    assert set(stats["dialect_constants"]) == {"serve/protocol.py",
                                               "net/framing.py"}
    assert set(stats["dispatchers"]) == {"frontend", "router", "peer",
                                         "serve-client"}
    # the router's driven-verb exclusions are on record, not silent
    # (MSG_WAL_SYNC: standbys tail their primary SHARD, not the router)
    assert stats["dispatchers"]["router"]["ignored"] == [
        "MSG_DSUM", "MSG_FRONTIER", "MSG_GC", "MSG_SLICE_PULL",
        "MSG_SLICE_PUSH", "MSG_WAL_SYNC"]
    # every reply frame the servers ignore is armed in the client
    client = stats["dispatchers"]["serve-client"]
    assert set(client["required"]) <= set(client["handled"])
