"""Slow-marked wrapper for the digest-sync bytes-on-the-wire sweep
(tools/chaos_soak.py --sync-curve — the SYNC_CURVE.json leg of the
chaos soak, DESIGN.md §19)."""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools"))


@pytest.mark.slow
def test_sync_curve_quick(tmp_path):
    """Quick sweep: quiescent digest rounds ship ZERO state lanes at
    bytes ≈ digest+vv, divergent digest rounds cost strictly fewer
    bytes than the δ ladder on the identical seeded op stream, and the
    digest regime converges under ChaosProxy faults race-free."""
    import chaos_soak

    out = str(tmp_path / "SYNC_CURVE.json")
    rc = chaos_soak.main(["--sync-curve", "--quick", "--detect-races",
                          "--out", out])
    assert rc == 0
    with open(out) as f:
        artifact = json.load(f)
    assert artifact["quiescent"]["digest_state_lanes"] == 0
    assert (artifact["quiescent"]["digest_bytes_per_round"]
            < artifact["quiescent"]["delta_bytes_per_round"])
    for leg in artifact["divergent"]:
        assert leg["ok"], leg
        assert (leg["digest"]["bytes_per_round"]
                < leg["delta"]["bytes_per_round"]), leg
    assert artifact["chaos"]["converged"]
    assert artifact["race_detection"]["races"] == []
