"""Router-tier tests (shard/router.py): dialect preservation, the
bitwise routed-vs-single-node pin, fan-out joins, spanning-op fan-out,
and per-shard degradation — all in-process (subprocess fleets are the
slow-marked fleet soak's job).
"""

import threading
import time

import numpy as np
import pytest

from go_crdt_playground_tpu.serve import ServeFrontend, protocol
from go_crdt_playground_tpu.serve.client import ServeClient
from go_crdt_playground_tpu.shard.router import ShardRouter

E, A = 64, 4
N_SHARDS = 3


class _Fleet:
    """N in-process frontends + a router, torn down in order."""

    def __init__(self, tmp_path, n_shards=N_SHARDS, **router_kw):
        self.frontends = [
            ServeFrontend(E, A, actor=i,
                          durable_dir=str(tmp_path / f"s{i}"),
                          max_batch=8, flush_ms=1.0, queue_depth=32)
            for i in range(n_shards)]
        self.addrs = {f"s{i}": fe.serve()
                      for i, fe in enumerate(self.frontends)}
        self.router = ShardRouter(self.addrs, E, seed=5, **router_kw)
        self.addr = self.router.serve()

    def owned_by(self, sid):
        return [e for e in range(E)
                if self.router.ring.shards[self.router._owner[e]] == sid]

    def close(self):
        self.router.close()
        for fe in self.frontends:
            fe.close()


@pytest.fixture()
def fleet(tmp_path):
    f = _Fleet(tmp_path)
    yield f
    f.close()


def test_routed_ingest_end_to_end(fleet):
    """An UNMODIFIED ServeClient against the router: ops ack, the
    QUERY fan-out unions membership across shards."""
    with ServeClient(fleet.addr) as c:
        c.add(1, 2, 3)
        c.add(40)
        c.delete(2)
        members, vv = c.members()
    assert members == [1, 3, 40]
    # 4 add ticks + 1 del tick, spread over the shards' actor lanes
    assert int(np.asarray(vv).sum()) == 5
    snap = fleet.router.recorder.snapshot()
    assert snap["counters"]["router.ops.forwarded"] == 3
    assert snap["counters"]["router.acks.relayed"] == 3


def test_routed_matches_single_node_bitwise(tmp_path):
    """The acceptance pin: the same op stream through router+fleet
    converges to the same state as single-node ingest — membership
    array bitwise-equal, and EACH shard's replica bitwise-equal to a
    reference node ingesting the sub-stream the ring assigns it."""
    import jax

    from go_crdt_playground_tpu.net.peer import Node

    fleet = _Fleet(tmp_path)
    stream = [(protocol.OP_ADD, [3, 9, 11]), (protocol.OP_DEL, [9]),
              (protocol.OP_ADD, [9, 20]), (protocol.OP_DEL, [3, 20]),
              (protocol.OP_ADD, [40, 41, 42, 43]), (protocol.OP_DEL, [41]),
              (protocol.OP_ADD, [0, 63])]
    try:
        with ServeClient(fleet.addr) as c:
            for kind, elems in stream:
                # synchronous: per-shard sub-stream order is the client
                # order restricted to that shard's keyspace
                c.submit_async(kind, elems).wait(30.0)
            members, _ = c.members()
        # reference 1: one node ingesting the whole stream
        single = Node(0, E, A)
        for kind, elems in stream:
            (single.add if kind == protocol.OP_ADD
             else single.delete)(*elems)
        np.testing.assert_array_equal(
            np.asarray(members),
            np.nonzero(np.asarray(single.state_slice().present))[0])
        # reference 2: per-shard bitwise — each shard replica equals a
        # node (same actor lane) fed exactly its ring-assigned keys
        for i, fe in enumerate(fleet.frontends):
            sid = f"s{i}"
            owned = set(fleet.owned_by(sid))
            ref = Node(i, E, A)
            for kind, elems in stream:
                mine = [e for e in elems if e in owned]
                if mine:
                    (ref.add if kind == protocol.OP_ADD
                     else ref.delete)(*mine)
            got, want = fe.node.state_slice(), ref.state_slice()
            for name in want._fields:
                np.testing.assert_array_equal(
                    np.asarray(getattr(got, name)),
                    np.asarray(getattr(want, name)),
                    err_msg=f"shard {sid} field {name}")
    finally:
        fleet.close()
    assert jax is not None


def test_spanning_op_acks_once(fleet):
    """An op whose keys span shards fans out but answers with ONE
    frame; the split is visible in the router counters."""
    all_elems = list(range(12))  # 12 keys over 3 shards: guaranteed span
    with ServeClient(fleet.addr) as c:
        c.add(*all_elems)
        members, _ = c.members()
    assert members == all_elems
    snap = fleet.router.recorder.snapshot()
    assert snap["counters"]["router.ops.split"] >= 1
    assert snap["counters"]["router.acks.relayed"] == 1


def test_router_rejects_invalid_and_duplicate(fleet):
    from go_crdt_playground_tpu.net import framing
    from go_crdt_playground_tpu.utils import wire

    with ServeClient(fleet.addr) as c:
        with pytest.raises(protocol.InvalidOp):
            c.add(E + 3)
        c.add(1)  # connection survives
    # duplicate keys, hand-crafted past the client encoder
    import socket as socket_mod

    body = bytearray()
    wire._put_varint(body, 5)
    body.append(protocol.OP_ADD)
    wire._put_varint(body, 0)
    wire._put_varint(body, 2)
    wire._put_varint(body, 7)
    wire._put_varint(body, 7)
    raw = socket_mod.create_connection(fleet.addr, timeout=10.0)
    try:
        framing.send_frame(raw, protocol.MSG_OP, bytes(body))
        msg_type, reply = framing.recv_frame(raw, timeout=10.0)
        assert msg_type == protocol.MSG_REJECT
        req_id, code, _ = protocol.decode_reject(reply)
        assert (req_id, code) == (5, protocol.REJECT_INVALID)
    finally:
        raw.close()


def test_dead_shard_degrades_typed_and_survivors_serve(fleet):
    """The per-shard degradation ladder: killing one shard turns ITS
    keyspace into typed ShardUnavailable rejects (breaker-gated, never
    a silent drop or a stall) while other shards' keyspaces keep
    acking and the MEMBERS fan-out serves the surviving union."""
    dead_sid = "s1"
    dead_keys = fleet.owned_by(dead_sid)
    live_keys = [e for e in range(E) if e not in set(dead_keys)]
    with ServeClient(fleet.addr) as c:
        c.add(live_keys[0])
        c.add(dead_keys[0])
        fleet.frontends[1].close()  # the shard goes away
        t0 = time.monotonic()
        with pytest.raises(protocol.ShardUnavailable):
            c.add(dead_keys[1])
        assert time.monotonic() - t0 < 5.0, "reject stalled"
        # breaker open now: the next op insta-rejects
        with pytest.raises(protocol.ShardUnavailable):
            c.add(dead_keys[2])
        c.add(live_keys[1])  # survivors keep serving
        members, _ = c.members()
    assert live_keys[0] in members and live_keys[1] in members
    # the dead shard's earlier key is simply absent from the partial
    # union — a correct CRDT lower bound, counted as partial
    assert dead_keys[0] not in members
    snap = fleet.router.recorder.snapshot()
    assert snap["counters"]["router.shed.unavailable"] >= 1
    assert snap["counters"]["router.queries.partial"] >= 1


def test_spanning_op_with_dead_shard_rejects_whole_op(fleet):
    """A spanning op with one unreachable owner resolves as ONE typed
    reject (sub-ops on live shards may have applied — idempotent, the
    client resubmits the whole op)."""
    dead_sid = "s2"
    dead_keys = fleet.owned_by(dead_sid)
    live_keys = fleet.owned_by("s0")
    fleet.frontends[2].close()
    with ServeClient(fleet.addr) as c:
        with pytest.raises(protocol.ShardUnavailable):
            c.add(live_keys[0], dead_keys[0])
        # the live half applied (at-least-once semantics)
        members, _ = c.members()
    assert live_keys[0] in members


def test_router_stats_fan_out_shapes(fleet):
    with ServeClient(fleet.addr) as c:
        c.add(1, 2, 3)
        snap = c.stats()
    # frontend-shaped top level (a single-node stats reader works) ...
    assert snap["counters"]["serve.ops.acked"] >= 1
    assert "observations" in snap
    # ... with the per-shard split and the aggregate alongside
    assert set(snap["shards"]) == {"s0", "s1", "s2"}
    assert all(s is not None for s in snap["shards"].values())
    agg = snap["aggregate"]["counters"]
    assert agg["serve.ops.acked"] == sum(
        s["counters"].get("serve.ops.acked", 0)
        for s in snap["shards"].values())
    assert snap["router"]["counters"]["router.stats"] == 1


def test_router_draining_rejects_typed(fleet):
    with ServeClient(fleet.addr) as c:
        c.add(1)
        fleet.router._draining.set()
        with pytest.raises(protocol.Draining):
            c.add(2)


def test_router_concurrent_clients_converge(fleet):
    """Pipelined concurrent clients through the router: every op
    resolves, the union is exactly the submitted set.  A typed
    ``Overloaded`` shed is NOT a failure — it is the protocol working
    under 2-core scheduling noise — and resolves the protocol way:
    idempotent resubmit."""
    n_clients, per_client = 4, 24
    errors = []

    def run(base):
        try:
            with ServeClient(fleet.addr) as c:
                todo = [(base + i) % E for i in range(per_client)]
                for _ in range(50):
                    ops = [(e, c.submit_async(protocol.OP_ADD, [e]))
                           for e in todo]
                    shed = []
                    for e, op in ops:
                        try:
                            op.wait(30.0)
                        except protocol.Overloaded:
                            shed.append(e)
                    if not shed:
                        return
                    todo = shed
                    time.sleep(0.01)
                errors.append(AssertionError(f"ops never landed: {todo}"))
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [threading.Thread(target=run, args=(w * per_client,))
               for w in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60.0)
    assert not errors, errors
    with ServeClient(fleet.addr) as c:
        members, _ = c.members()
    want = sorted({(w * per_client + i) % E
                   for w in range(n_clients) for i in range(per_client)})
    assert members == want
