"""Router-tier tests (shard/router.py): dialect preservation, the
bitwise routed-vs-single-node pin, fan-out joins, spanning-op fan-out,
and per-shard degradation — all in-process (subprocess fleets are the
slow-marked fleet soak's job).
"""

import threading
import time

import numpy as np
import pytest

from go_crdt_playground_tpu.serve import ServeFrontend, protocol
from go_crdt_playground_tpu.serve.client import ServeClient
from go_crdt_playground_tpu.shard.router import ShardRouter

E, A = 64, 4
N_SHARDS = 3


class _Fleet:
    """N in-process frontends + a router, torn down in order."""

    def __init__(self, tmp_path, n_shards=N_SHARDS, **router_kw):
        self.frontends = [
            ServeFrontend(E, A, actor=i,
                          durable_dir=str(tmp_path / f"s{i}"),
                          max_batch=8, flush_ms=1.0, queue_depth=32)
            for i in range(n_shards)]
        self.addrs = {f"s{i}": fe.serve()
                      for i, fe in enumerate(self.frontends)}
        self.router = ShardRouter(self.addrs, E, seed=5, **router_kw)
        self.addr = self.router.serve()

    def owned_by(self, sid):
        return [e for e in range(E)
                if self.router.ring.shards[self.router._owner[e]] == sid]

    def close(self):
        self.router.close()
        for fe in self.frontends:
            fe.close()


@pytest.fixture()
def fleet(tmp_path):
    f = _Fleet(tmp_path)
    yield f
    f.close()


def test_routed_ingest_end_to_end(fleet):
    """An UNMODIFIED ServeClient against the router: ops ack, the
    QUERY fan-out unions membership across shards."""
    with ServeClient(fleet.addr) as c:
        c.add(1, 2, 3)
        c.add(40)
        c.delete(2)
        members, vv = c.members()
    assert members == [1, 3, 40]
    # 4 add ticks + 1 del tick, spread over the shards' actor lanes
    assert int(np.asarray(vv).sum()) == 5
    snap = fleet.router.recorder.snapshot()
    assert snap["counters"]["router.ops.forwarded"] == 3
    assert snap["counters"]["router.acks.relayed"] == 3


def test_routed_matches_single_node_bitwise(tmp_path):
    """The acceptance pin: the same op stream through router+fleet
    converges to the same state as single-node ingest — membership
    array bitwise-equal, and EACH shard's replica bitwise-equal to a
    reference node ingesting the sub-stream the ring assigns it."""
    import jax

    from go_crdt_playground_tpu.net.peer import Node

    fleet = _Fleet(tmp_path)
    stream = [(protocol.OP_ADD, [3, 9, 11]), (protocol.OP_DEL, [9]),
              (protocol.OP_ADD, [9, 20]), (protocol.OP_DEL, [3, 20]),
              (protocol.OP_ADD, [40, 41, 42, 43]), (protocol.OP_DEL, [41]),
              (protocol.OP_ADD, [0, 63])]
    try:
        with ServeClient(fleet.addr) as c:
            for kind, elems in stream:
                # synchronous: per-shard sub-stream order is the client
                # order restricted to that shard's keyspace
                c.submit_async(kind, elems).wait(30.0)
            members, _ = c.members()
        # reference 1: one node ingesting the whole stream
        single = Node(0, E, A)
        for kind, elems in stream:
            (single.add if kind == protocol.OP_ADD
             else single.delete)(*elems)
        np.testing.assert_array_equal(
            np.asarray(members),
            np.nonzero(np.asarray(single.state_slice().present))[0])
        # reference 2: per-shard bitwise — each shard replica equals a
        # node (same actor lane) fed exactly its ring-assigned keys
        for i, fe in enumerate(fleet.frontends):
            sid = f"s{i}"
            owned = set(fleet.owned_by(sid))
            ref = Node(i, E, A)
            for kind, elems in stream:
                mine = [e for e in elems if e in owned]
                if mine:
                    (ref.add if kind == protocol.OP_ADD
                     else ref.delete)(*mine)
            got, want = fe.node.state_slice(), ref.state_slice()
            for name in want._fields:
                np.testing.assert_array_equal(
                    np.asarray(getattr(got, name)),
                    np.asarray(getattr(want, name)),
                    err_msg=f"shard {sid} field {name}")
    finally:
        fleet.close()
    assert jax is not None


def test_spanning_op_acks_once(fleet):
    """An op whose keys span shards fans out but answers with ONE
    frame; the split is visible in the router counters."""
    all_elems = list(range(12))  # 12 keys over 3 shards: guaranteed span
    with ServeClient(fleet.addr) as c:
        c.add(*all_elems)
        members, _ = c.members()
    assert members == all_elems
    snap = fleet.router.recorder.snapshot()
    assert snap["counters"]["router.ops.split"] >= 1
    assert snap["counters"]["router.acks.relayed"] == 1


def test_router_rejects_invalid_and_duplicate(fleet):
    from go_crdt_playground_tpu.net import framing
    from go_crdt_playground_tpu.utils import wire

    with ServeClient(fleet.addr) as c:
        with pytest.raises(protocol.InvalidOp):
            c.add(E + 3)
        c.add(1)  # connection survives
    # duplicate keys, hand-crafted past the client encoder
    import socket as socket_mod

    body = bytearray()
    wire._put_varint(body, 5)
    body.append(protocol.OP_ADD)
    wire._put_varint(body, 0)
    wire._put_varint(body, 2)
    wire._put_varint(body, 7)
    wire._put_varint(body, 7)
    raw = socket_mod.create_connection(fleet.addr, timeout=10.0)
    try:
        framing.send_frame(raw, protocol.MSG_OP, bytes(body))
        msg_type, reply = framing.recv_frame(raw, timeout=10.0)
        assert msg_type == protocol.MSG_REJECT
        req_id, code, _ = protocol.decode_reject(reply)
        assert (req_id, code) == (5, protocol.REJECT_INVALID)
    finally:
        raw.close()


def test_dead_shard_degrades_typed_and_survivors_serve(fleet):
    """The per-shard degradation ladder: killing one shard turns ITS
    keyspace into typed ShardUnavailable rejects (breaker-gated, never
    a silent drop or a stall) while other shards' keyspaces keep
    acking and the MEMBERS fan-out serves the surviving union."""
    dead_sid = "s1"
    dead_keys = fleet.owned_by(dead_sid)
    live_keys = [e for e in range(E) if e not in set(dead_keys)]
    with ServeClient(fleet.addr) as c:
        c.add(live_keys[0])
        c.add(dead_keys[0])
        fleet.frontends[1].close()  # the shard goes away
        t0 = time.monotonic()
        with pytest.raises(protocol.ShardUnavailable):
            c.add(dead_keys[1])
        assert time.monotonic() - t0 < 5.0, "reject stalled"
        # breaker open now: the next op insta-rejects
        with pytest.raises(protocol.ShardUnavailable):
            c.add(dead_keys[2])
        c.add(live_keys[1])  # survivors keep serving
        members, _ = c.members()
    assert live_keys[0] in members and live_keys[1] in members
    # the dead shard's earlier key is simply absent from the partial
    # union — a correct CRDT lower bound, counted as partial
    assert dead_keys[0] not in members
    snap = fleet.router.recorder.snapshot()
    assert snap["counters"]["router.shed.unavailable"] >= 1
    assert snap["counters"]["router.queries.partial"] >= 1


def test_spanning_op_with_dead_shard_rejects_whole_op(fleet):
    """A spanning op with one unreachable owner resolves as ONE typed
    reject (sub-ops on live shards may have applied — idempotent, the
    client resubmits the whole op)."""
    dead_sid = "s2"
    dead_keys = fleet.owned_by(dead_sid)
    live_keys = fleet.owned_by("s0")
    fleet.frontends[2].close()
    with ServeClient(fleet.addr) as c:
        with pytest.raises(protocol.ShardUnavailable):
            c.add(live_keys[0], dead_keys[0])
        # the live half applied (at-least-once semantics)
        members, _ = c.members()
    assert live_keys[0] in members


def test_router_stats_fan_out_shapes(fleet):
    with ServeClient(fleet.addr) as c:
        c.add(1, 2, 3)
        snap = c.stats()
    # frontend-shaped top level (a single-node stats reader works) ...
    assert snap["counters"]["serve.ops.acked"] >= 1
    assert "observations" in snap
    # ... with the per-shard split and the aggregate alongside
    assert set(snap["shards"]) == {"s0", "s1", "s2"}
    assert all(s is not None for s in snap["shards"].values())
    agg = snap["aggregate"]["counters"]
    assert agg["serve.ops.acked"] == sum(
        s["counters"].get("serve.ops.acked", 0)
        for s in snap["shards"].values())
    assert snap["router"]["counters"]["router.stats"] == 1


def test_router_draining_rejects_typed(fleet):
    with ServeClient(fleet.addr) as c:
        c.add(1)
        fleet.router.host._draining.set()
        with pytest.raises(protocol.Draining):
            c.add(2)


def test_router_concurrent_clients_converge(fleet):
    """Pipelined concurrent clients through the router: every op
    resolves, the union is exactly the submitted set.  A typed
    ``Overloaded`` shed is NOT a failure — it is the protocol working
    under 2-core scheduling noise — and resolves the protocol way:
    idempotent resubmit."""
    n_clients, per_client = 4, 24
    errors = []

    def run(base):
        try:
            with ServeClient(fleet.addr) as c:
                todo = [(base + i) % E for i in range(per_client)]
                for _ in range(50):
                    ops = [(e, c.submit_async(protocol.OP_ADD, [e]))
                           for e in todo]
                    shed = []
                    for e, op in ops:
                        try:
                            op.wait(30.0)
                        except protocol.Overloaded:
                            shed.append(e)
                    if not shed:
                        return
                    todo = shed
                    time.sleep(0.01)
                errors.append(AssertionError(f"ops never landed: {todo}"))
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [threading.Thread(target=run, args=(w * per_client,))
               for w in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60.0)
    assert not errors, errors
    with ServeClient(fleet.addr) as c:
        members, _ = c.members()
    want = sorted({(w * per_client + i) % E
                   for w in range(n_clients) for i in range(per_client)})
    assert members == want


# ---------------------------------------------------------------------------
# live resharding (shard/handoff.py, DESIGN.md §18)
# ---------------------------------------------------------------------------


def test_live_join_and_leave_zero_loss(tmp_path, capsys):
    """The tentpole round trip, in-process: populate the keyspace,
    JOIN a third shard live (fence → slice transfer → atomic swap),
    then LEAVE it again via the CLI admin verb.  Zero membership loss
    at every step, the moved count matches remap_fraction's prediction
    exactly, the joiner's replica really holds the moved slice, and a
    delete applied at the new owner is never shadowed by the donor's
    stale copy (no double-serve) nor resurrected by the leave."""
    from go_crdt_playground_tpu.__main__ import main as cli_main
    from go_crdt_playground_tpu.shard.ring import remap_fraction

    fleet = _Fleet(tmp_path, n_shards=2)
    joiner = ServeFrontend(E, A, actor=2,
                           durable_dir=str(tmp_path / "joiner"),
                           max_batch=8, flush_ms=1.0, queue_depth=32)
    joiner_addr = joiner.serve()
    try:
        with ServeClient(fleet.addr, timeout=60.0) as c:
            c.add(*range(0, E, 2))
            c.add(*range(1, E, 2))
            c.delete(3)
            before, _ = c.members()
            ring0 = c.stats()["ring"]

            ok, detail = c.reshard(protocol.RESHARD_JOIN, "s2",
                                   joiner_addr, timeout=60.0)
            assert ok, detail
            after_join, _ = c.members()
            assert after_join == before, "join lost/invented members"

            # the router's accounting == the ring math, cross-checked
            r0 = fleet.router.route().ring.without_shard("s2")
            r1 = fleet.router.route().ring
            rm = remap_fraction(r0.owner_map(E), r1.owner_map(E),
                                r0.shards, r1.shards)
            assert detail["moved"] == rm["moved"] > 0
            assert detail["moved_transferred"] == rm["moved"]
            assert detail["fraction"] == pytest.approx(rm["fraction"])
            assert detail["gratuitous"] == 0
            assert detail["generation"] == 1
            ring1 = c.stats()["ring"]
            assert ring1["generation"] == 1
            assert ring1["digest"] != ring0["digest"]
            assert sorted(ring1["shards"]) == ["s0", "s1", "s2"]

            # the joiner REALLY owns its slice: its replica holds every
            # moved live element (transferred state, not routing smoke)
            rt = fleet.router.route()
            owned = [e for e in range(E) if rt.owner_sid(e) == "s2"]
            assert len(owned) == detail["moved"]
            joiner_members = set(int(x) for x in joiner.node.members())
            assert set(owned) - {3} <= joiner_members

            # no double-serve: a delete at the new owner sticks even
            # though the donor still holds a stale present copy
            victim = next(e for e in owned if e != 3)
            c.delete(victim)
            m, _ = c.members()
            assert victim not in m

            # LEAVE via the CLI admin verb (the operator surface)
            host_, port_ = fleet.addr
            rc = cli_main(["reshard", "--router", f"{host_}:{port_}",
                           "--leave", "s2"])
            assert rc == 0
            capsys.readouterr()  # swallow the CLI's JSON print
            # the left shard's member-cache entry is evicted with its
            # link (nothing would ever refresh it)
            with fleet.router._member_cache_lock:
                assert "s2" not in fleet.router._member_cache
            m2, _ = c.members()
            assert m2 == m, "leave lost/invented members"
            assert victim not in m2, "leave resurrected a deleted element"
            ring2 = c.stats()["ring"]
            assert ring2["generation"] == 2
            assert ring2["digest"] == ring0["digest"], \
                "leave back to the original membership must restore " \
                "the original owner-map digest"
            # ops route normally post-reshard
            c.add(victim)
            m3, _ = c.members()
            assert victim in m3
    finally:
        joiner.close()
        fleet.close()


def test_failed_join_leaves_old_ring_serving(tmp_path):
    """Failure is the main path: a join whose recipient never answers
    aborts (typed failure reply, bounded by the transfer deadline) and
    the OLD ring keeps serving — same generation, same digest, ops
    still ack."""
    fleet = _Fleet(tmp_path, n_shards=2, transfer_timeout_s=1.5)
    try:
        with ServeClient(fleet.addr, timeout=30.0) as c:
            c.add(1, 2, 3)
            ring0 = c.stats()["ring"]
            t0 = time.monotonic()
            ok, detail = c.reshard(protocol.RESHARD_JOIN, "sX",
                                   ("127.0.0.1", 1), timeout=30.0)
            assert not ok
            assert "reason" in detail
            assert time.monotonic() - t0 < 15.0, "abort was unbounded"
            ring1 = c.stats()["ring"]
            assert ring1["generation"] == ring0["generation"]
            assert ring1["digest"] == ring0["digest"]
            c.add(4)  # the old ring is fully serving
            m, _ = c.members()
            assert m == [1, 2, 3, 4]
        snap = fleet.router.recorder.snapshot()
        assert snap["counters"]["router.reshard.aborts"] == 1
        assert snap["counters"].get("router.reshard.commits", 0) == 0
    finally:
        fleet.close()


def test_fence_rejects_typed_moving(fleet):
    """The fence semantics, deterministically: a fenced element's op
    gets the typed retryable KeyspaceMoving (never applied anywhere);
    unfenced keyspace keeps acking; clearing the fence re-admits."""
    import numpy as np

    fenced_e, free_e = 7, 8
    fence = np.zeros(E, bool)
    fence[fenced_e] = True
    with ServeClient(fleet.addr, timeout=10.0) as c:
        c.add(free_e)
        fleet.router.set_fence(fence)
        with pytest.raises(protocol.KeyspaceMoving):
            c.add(fenced_e)
        c.add(free_e)  # unfenced keyspace unaffected
        # spanning op touching the fence: whole op rejected typed
        with pytest.raises(protocol.KeyspaceMoving):
            c.add(fenced_e, free_e)
        fleet.router.clear_fence()
        c.add(fenced_e)  # the retry lands after the fence drops
        m, _ = c.members()
    assert fenced_e in m
    snap = fleet.router.recorder.snapshot()
    assert snap["counters"]["router.shed.moving"] == 2
    # the fenced op was never applied anywhere: exactly one add of
    # fenced_e reached a shard (the post-clear one)
    assert fleet.router.route().fence is None


def test_router_restart_adopts_committed_ring(tmp_path):
    """Ring persistence: a committed swap survives a router restart
    (the record wins over CLI flags); a staged/aborted epoch does not;
    mismatched (E, seed) flags are refused loudly."""
    import json
    import os

    from go_crdt_playground_tpu.shard.handoff import RING_FILE
    from go_crdt_playground_tpu.shard.ring import HashRing

    state_dir = str(tmp_path / "router-state")
    os.makedirs(state_dir)
    ring = HashRing(["a", "b", "c"], seed=5)
    owners = ring.owner_map(E)
    rec = {"epoch": 4, "phase": "committed", "generation": 3,
           "seed": 5, "elements": E,
           "shards": {"a": ["127.0.0.1", 1111], "b": ["127.0.0.1", 2222],
                      "c": ["127.0.0.1", 3333]},
           "digest": ring.digest(E, owners)}
    with open(os.path.join(state_dir, RING_FILE), "w") as f:
        json.dump(rec, f)

    router = ShardRouter({"zz": ("127.0.0.1", 9)}, E, seed=5,
                         state_dir=state_dir)
    try:
        info = router.route().info()
        assert info["generation"] == 3
        assert sorted(info["shards"]) == ["a", "b", "c"]
        assert info["digest"] == rec["digest"]
        assert router.shard_addr("b") == ("127.0.0.1", 2222)
        assert router.handoff._epoch == 4  # monotone across restarts
    finally:
        router.close()

    # flags disagreeing with the committed record: refuse, don't guess
    with pytest.raises(ValueError):
        ShardRouter({"zz": ("127.0.0.1", 9)}, E, seed=6,
                    state_dir=state_dir)

    # an aborted/staged record is NOT adopted
    rec["phase"] = "aborted"
    with open(os.path.join(state_dir, RING_FILE), "w") as f:
        json.dump(rec, f)
    router = ShardRouter({"zz": ("127.0.0.1", 9)}, E, seed=5,
                         state_dir=state_dir)
    try:
        assert list(router.route().ring.shards) == ["zz"]
        assert router.route().generation == 0
    finally:
        router.close()


def test_reshard_staging_failures_are_typed(fleet):
    """Verbs that cannot even stage (duplicate join id, unknown leave
    id) reply typed failure without touching the ring or any shard."""
    with ServeClient(fleet.addr, timeout=10.0) as c:
        ring0 = c.stats()["ring"]
        ok, d = c.reshard(protocol.RESHARD_JOIN, "s0",
                          ("127.0.0.1", 9), timeout=10.0)
        assert not ok and "already in the ring" in d["reason"]
        ok, d = c.reshard(protocol.RESHARD_LEAVE, "nope", timeout=10.0)
        assert not ok and "not in ring" in d["reason"]
        # a reshard timeout past the CONNECTION timeout is refused
        # loudly (the reader would time the idle admin connection out
        # first and mis-report a commit as ConnectionError)
        with pytest.raises(ValueError):
            c.reshard(protocol.RESHARD_LEAVE, "s1", timeout=999.0)
        assert c.stats()["ring"] == ring0


# ---------------------------------------------------------------------------
# digest-guarded member cache (ROADMAP digest rung b, DESIGN.md §20)
# ---------------------------------------------------------------------------


def _cache_counters(router):
    snap = router.recorder.snapshot()["counters"]
    return (snap.get("router.member_cache.hits", 0),
            snap.get("router.member_cache.refreshes", 0))


def test_member_cache_hits_quiescent_refreshes_on_change(fleet):
    """The O(diff) read contract: the first QUERY populates one cache
    entry per shard, quiescent repeats serve every shard from cache
    (summary compare only — no MEMBERS pull), and a write touching ONE
    shard's keyspace refreshes exactly that shard's entry."""
    with ServeClient(fleet.addr) as c:
        c.add(1, 2, 3)
        m1, vv1 = c.members()
        assert _cache_counters(fleet.router) == (0, N_SHARDS)
        # quiescent repeat: identical reply, all shards hit
        m2, vv2 = c.members()
        assert m2 == m1
        np.testing.assert_array_equal(np.asarray(vv2), np.asarray(vv1))
        assert _cache_counters(fleet.router) == (N_SHARDS, N_SHARDS)
        # advance ONE shard: its key is stale, the others still hit
        lone = fleet.owned_by("s0")[0]
        c.add(lone)
        m3, _ = c.members()
        assert lone in m3
        assert _cache_counters(fleet.router) == (
            2 * N_SHARDS - 1, N_SHARDS + 1)


def test_member_cache_legacy_shard_pinned_uncached(fleet):
    """A pre-digest shard (DSUM answered with the legacy unexpected-
    frame error) costs ONE failed probe — on a THROWAWAY dial, never
    the shared link client (the legacy frontend ends the connection
    on the unknown frame, which would tear down in-flight ops) — is
    pinned to the uncached path for good, and never poisons the
    other shards' caching.  The pin requires the TYPED classification
    (_DsumUnsupported: the server's own MSG_ERROR) — a transient
    error whose text merely contains the same words must not pin
    (covered by the transient test below)."""
    from go_crdt_playground_tpu.shard.router import _DsumUnsupported

    link = fleet.router.links_snapshot()["s0"]
    calls = {"n": 0}

    def legacy_dsum():
        calls["n"] += 1
        raise _DsumUnsupported("shard s0 is pre-digest: unexpected "
                               "frame type 32")

    link.digest_summary_probe = legacy_dsum
    with ServeClient(fleet.addr) as c:
        c.add(1, 2, 3)
        m1, _ = c.members()
        assert calls["n"] == 1
        assert "s0" in fleet.router._dsum_unsupported
        m2, _ = c.members()
        assert m2 == m1
        assert calls["n"] == 1, "legacy shard probed more than once"
    with fleet.router._member_cache_lock:
        assert set(fleet.router._member_cache) == {"s1", "s2"}
    assert _cache_counters(fleet.router) == (
        N_SHARDS - 1, N_SHARDS - 1)


def test_member_cache_transient_dsum_failure_stays_cacheable(fleet):
    """A TRANSIENT summary failure (dead shard, torn link — anything
    without the legacy-frame signature) must NOT pin the shard
    uncached: the query falls through to members() for that round and
    the next round probes the summary again."""
    from go_crdt_playground_tpu.shard.router import _Unreachable

    link = fleet.router.links_snapshot()["s0"]
    real_probe = link.digest_summary_probe
    calls = {"n": 0}

    def flaky_probe():
        calls["n"] += 1
        if calls["n"] == 1:
            # a desynced reply's locally-synthesized message CONTAINS
            # the legacy text — the typed classification must still
            # treat it as transient, never pin
            raise _Unreachable("shard s0 dsum probe: server went "
                               "away: unexpected frame type 9")
        return real_probe()

    link.digest_summary_probe = flaky_probe
    with ServeClient(fleet.addr) as c:
        c.add(1, 2, 3)
        m1, _ = c.members()
        assert "s0" not in fleet.router._dsum_unsupported
        m2, _ = c.members()  # second round probes again and caches
        assert m2 == m1
    assert calls["n"] == 2
    with fleet.router._member_cache_lock:
        assert "s0" in fleet.router._member_cache
