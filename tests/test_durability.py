"""Durability ladder: verified generational checkpoints, WAL-backed
crash recovery, storage-fault tolerance (DESIGN.md §14).

Covers the recovery invariants the crash soak exercises end-to-end, at
unit scale: digests refuse bit rot, the generational store falls back
past a corrupt newest generation (never aborts), generation fencing
refuses regression, kill → restore_durable replays the WAL tail, and a
restored supervisor catches up with the fleet over the FULL-state
first-contact branch.
"""

import os

import numpy as np
import pytest

from go_crdt_playground_tpu.models import awset_delta
from go_crdt_playground_tpu.models.digest import array_digest, state_digest
from go_crdt_playground_tpu.obs import Recorder
from go_crdt_playground_tpu.utils import checkpoint as ckpt
from go_crdt_playground_tpu.utils.checkpoint import (CheckpointCorrupt,
                                                     CheckpointStore,
                                                     GenerationRegression)


def _state():
    return awset_delta.init(1, 16, 3, actors=np.asarray([0], np.uint32))


def _flip_bit(path, offset=None):
    size = os.path.getsize(path)
    offset = size // 2 if offset is None else offset
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 1]))


# -- digests -----------------------------------------------------------------


def test_array_digest_covers_dtype_and_shape():
    a = np.arange(8, dtype=np.uint32)
    assert array_digest(a) != array_digest(a.astype(np.int32))
    assert array_digest(a) != array_digest(a.reshape(2, 4))
    assert array_digest(a) == array_digest(a.copy())


def test_state_digest_stable_and_field_sensitive():
    st = _state()
    assert state_digest(st) == state_digest(_state())
    st2 = st._replace(vv=st.vv + 1)
    assert state_digest(st) != state_digest(st2)
    with pytest.raises(TypeError):
        state_digest({"not": "a state"})


# -- verify-on-restore -------------------------------------------------------


def test_bit_flip_refused_on_restore(tmp_path):
    p = str(tmp_path / "ck")
    ckpt.save_checkpoint(p, _state())
    assert ckpt.restore_checkpoint(p) is not None  # intact loads
    _flip_bit(p)  # default offset lands inside the array data region
    with pytest.raises(CheckpointCorrupt):
        ckpt.restore_checkpoint(p)


def test_bit_flip_anywhere_never_loads_silently_wrong(tmp_path):
    """The full integrity invariant: a one-bit flip at ANY offset either
    raises CheckpointCorrupt (data or manifest hit) or restores a state
    bitwise equal to the original (zip-metadata hit) — silent wrong data
    is never an outcome."""
    p = str(tmp_path / "ck")
    orig = _state()
    ckpt.save_checkpoint(p, orig)
    size = os.path.getsize(p)
    with open(p, "rb") as f:
        pristine = f.read()
    for offset in range(7, size, max(1, size // 23)):
        with open(p, "wb") as f:
            f.write(pristine)
        _flip_bit(p, offset=offset)
        try:
            got = ckpt.restore_checkpoint(p, to_device=False)
        except (CheckpointCorrupt, ValueError):
            continue
        for name in orig._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(got.state, name)),
                np.asarray(getattr(orig, name)),
                err_msg=f"silent corruption at offset {offset}: {name}")


def test_truncated_container_is_checkpoint_corrupt(tmp_path):
    p = str(tmp_path / "ck")
    ckpt.save_checkpoint(p, _state())
    with open(p, "r+b") as f:
        f.truncate(os.path.getsize(p) // 2)
    with pytest.raises(CheckpointCorrupt):
        ckpt.restore_checkpoint(p)


def test_tmp_files_swept_on_save_and_restore(tmp_path):
    stray = tmp_path / ".ckpt-tmp-stray"
    stray.write_bytes(b"crash leftover")
    p = str(tmp_path / "ck")
    ckpt.save_checkpoint(p, _state())
    assert not stray.exists(), "save must sweep stale tmp files"
    stray.write_bytes(b"again")
    ckpt.restore_checkpoint(p)
    assert not stray.exists(), "restore must sweep stale tmp files"


def test_unknown_state_type_warns_and_counts(tmp_path):
    p = str(tmp_path / "ck")
    ckpt.save_checkpoint(p, _state())
    # rewrite the manifest's state type to something this build lacks
    import json

    with np.load(p) as z:
        manifest = json.loads(z["__manifest__"].tobytes().decode())
        arrays = {k: z[k] for k in z.files if k != "__manifest__"}
    manifest["state_type"] = "FutureState"
    blob = np.frombuffer(json.dumps(manifest).encode(), np.uint8)
    np.savez(p, __manifest__=blob, **arrays)
    os.replace(p + ".npz" if os.path.exists(p + ".npz") else p, p)
    rec = Recorder()
    with pytest.warns(RuntimeWarning, match="unknown"):
        got = ckpt.restore_checkpoint(p, verify=False, recorder=rec)
    assert isinstance(got.state, dict)
    assert rec.snapshot()["counters"]["restore.unknown_type"] == 1


# -- generational store ------------------------------------------------------


def test_store_generations_monotonic_and_pruned(tmp_path):
    store = CheckpointStore(str(tmp_path / "store"), keep=2)
    gens = [store.save(_state()) for _ in range(5)]
    assert gens == [1, 2, 3, 4, 5]
    assert store.generations() == [4, 5]  # keep=2 pruned the rest
    gen, ck = store.restore()
    assert gen == 5
    assert ck.generation == 5


def test_store_falls_back_past_corrupt_newest(tmp_path):
    rec = Recorder()
    store = CheckpointStore(str(tmp_path / "store"), keep=3, recorder=rec)
    for _ in range(3):
        store.save(_state())
    _flip_bit(store.path_for(3))
    gen, _ = store.restore()
    assert gen == 2, "corrupt newest must fall back to K-1"
    snap = rec.snapshot()
    assert snap["counters"]["restore.fallbacks"] == 1
    assert snap["gauges"]["restore.generation"] == 2


def test_store_all_corrupt_raises_checkpoint_corrupt(tmp_path):
    store = CheckpointStore(str(tmp_path / "store"), keep=3)
    store.save(_state())
    store.save(_state())
    _flip_bit(store.path_for(1))
    _flip_bit(store.path_for(2))
    with pytest.raises(CheckpointCorrupt):
        store.restore()


def test_store_generation_fence(tmp_path):
    store = CheckpointStore(str(tmp_path / "store"), keep=3)
    store.save(_state())
    with pytest.raises(GenerationRegression):
        store.restore(min_generation=2)
    # and a corrupt newest that forces fallback BELOW the fence refuses
    store.save(_state())
    _flip_bit(store.path_for(2))
    with pytest.raises(GenerationRegression):
        store.restore(min_generation=2)


def test_store_rejects_generation_spoof(tmp_path):
    store = CheckpointStore(str(tmp_path / "store"), keep=5)
    store.save(_state())
    store.save(_state())
    # rename the OLD generation over the newest slot: file name and
    # manifest now disagree, so restore must skip it (spoof), landing on
    # nothing valid above gen-1... the renamed file is gone from slot 1
    os.replace(store.path_for(1), store.path_for(7))
    gen, _ = store.restore()
    assert gen == 2, "a stale file renamed forward must not win"


def test_store_empty_raises_file_not_found(tmp_path):
    store = CheckpointStore(str(tmp_path / "store"))
    with pytest.raises(FileNotFoundError):
        store.restore()


def test_sharded_checkpoint_generation_fence(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    from go_crdt_playground_tpu.utils.checkpoint_sharded import (
        restore_checkpoint_sharded, save_checkpoint_sharded)

    p = str(tmp_path / "sharded")
    save_checkpoint_sharded(p, _state(), generation=3)
    got = restore_checkpoint_sharded(p, min_generation=3)
    assert got.generation == 3
    with pytest.raises(GenerationRegression):
        restore_checkpoint_sharded(p, min_generation=4)
    # a crash mid-save leaves a half-manifest: restore sweeps it
    stray = os.path.join(p, ".manifest-tmp")
    with open(stray, "w") as f:
        f.write("{")
    restore_checkpoint_sharded(p, min_generation=0)
    assert not os.path.exists(stray)


# -- storage fault vocabulary ------------------------------------------------


def test_storage_faults_deterministic_and_counted(tmp_path):
    from go_crdt_playground_tpu.net.faults import (StorageFaults,
                                                   StorageScenario)

    def run(seed):
        p = str(tmp_path / f"blob-{seed}")
        with open(p, "wb") as f:
            f.write(bytes(range(256)) * 4)
        sf = StorageFaults(StorageScenario(
            torn_write_rate=0.3, bit_flip_rate=0.3, zero_fill_rate=0.3),
            seed=seed)
        verbs = [sf.inject(p) for _ in range(12)]
        with open(p, "rb") as f:
            return verbs, f.read(), sf.counters()

    v1, d1, c1 = run(7)
    os.unlink(str(tmp_path / "blob-7"))
    v2, d2, _ = run(7)
    v3, d3, _ = run(8)
    assert v1 == v2 and d1 == d2, "same seed must replay the same faults"
    assert (v1, d1) != (v3, d3)
    assert c1["inject_calls"] == 12
    fired = sum(1 for v in v1 if v is not None)
    assert fired == c1["torn_writes"] + c1["bit_flips"] + c1["zero_fills"]
    assert fired > 0, "a 0.9 total rate that never fires is a broken test"


def test_storage_faults_explicit_verbs(tmp_path):
    from go_crdt_playground_tpu.net.faults import StorageFaults

    p = str(tmp_path / "blob")
    payload = bytes(range(200))
    with open(p, "wb") as f:
        f.write(payload)
    sf = StorageFaults(seed=1)
    sf.torn_write(p, cut_bytes=10)
    assert os.path.getsize(p) == 190
    sf.bit_flip(p, offset=0, bit=0)
    with open(p, "rb") as f:
        assert f.read(1)[0] == payload[0] ^ 1
    sf.zero_fill(p, offset=5, span=3)
    with open(p, "rb") as f:
        assert f.read()[5:8] == b"\x00\x00\x00"
    c = sf.counters()
    assert (c["torn_writes"], c["bit_flips"], c["zero_fills"]) == (1, 1, 1)


def test_bit_flip_array_always_defeats_restore(tmp_path):
    """The checkpoint-aware corruption verb must produce a flip the
    restore-time verification CATCHES, at every seed — that is its whole
    reason to exist over the blind tail flip."""
    from go_crdt_playground_tpu.net.faults import StorageFaults

    for seed in range(8):
        p = str(tmp_path / f"ck-{seed}")
        ckpt.save_checkpoint(p, _state())
        StorageFaults(seed=seed).bit_flip_array(p)
        with pytest.raises((CheckpointCorrupt, ValueError)):
            ckpt.restore_checkpoint(p)


def test_chaos_scenario_carries_storage_namespace():
    from go_crdt_playground_tpu.net.faults import (ChaosScenario,
                                                   StorageScenario)

    s = ChaosScenario(drop_rate=0.1,
                      storage=StorageScenario(torn_write_rate=0.2))
    assert s.storage.torn_write_rate == 0.2
    with pytest.raises(ValueError):
        StorageScenario(bit_flip_rate=1.5)


# -- kill -> restore -> catch-up ---------------------------------------------


def test_node_kill_restore_replays_wal_tail(tmp_path):
    from go_crdt_playground_tpu.net import Node
    from go_crdt_playground_tpu.utils.wal import DeltaWal

    d = str(tmp_path / "durable")
    rec = Recorder()
    node = Node(0, 32, 2, recorder=rec,
                wal=DeltaWal(os.path.join(d, "wal"), recorder=rec))
    store = CheckpointStore(d, recorder=rec)
    node.add(1, 2, 3)
    gen = node.save_durable(store)
    assert gen == 1
    assert node.wal.record_count() == 0, "checkpoint truncates the WAL"
    node.add(4)
    node.delete(2)
    node.wal.close()  # SIGKILL analogue: no checkpoint of the tail ops

    rec2 = Recorder()
    back = Node.restore_durable(d, recorder=rec2)
    assert set(int(e) for e in back.members()) == {1, 3, 4}
    assert back.generation == 1
    assert rec2.snapshot()["counters"]["wal.records"] >= 1
    back.wal.close()


def test_supervisor_restore_durable_full_catch_up_under_chaos(tmp_path):
    """kill -> restore -> FULL-state catch-up converges, behind a lossy
    proxy, with a corrupted newest checkpoint forcing the K-1 fallback
    on the way (the ISSUE's chaos-restore acceptance test)."""
    from go_crdt_playground_tpu.net import (ChaosProxy, ChaosScenario, Node,
                                            StorageFaults, SyncSupervisor)
    from go_crdt_playground_tpu.utils.backoff import BackoffPolicy

    d = str(tmp_path / "durable")
    rec = Recorder()
    peer = Node(1, 32, 2, recorder=Recorder(),
                conn_timeout_s=5.0, hello_timeout_s=0.5)
    peer_addr = peer.serve()
    peer.add(20, 21, 22)
    proxy = ChaosProxy(peer_addr, seed=5,
                       scenario=ChaosScenario(drop_rate=0.3))
    lossy_addr = ("127.0.0.1", proxy.port)
    try:
        node = Node(0, 32, 2, recorder=rec, conn_timeout_s=5.0,
                    hello_timeout_s=0.5)
        sup = SyncSupervisor(
            node, [lossy_addr],
            policy=BackoffPolicy(base_s=0.005, cap_s=0.05, max_retries=3),
            sync_timeout_s=2.0, breaker_threshold=5,
            breaker_cooldown_s=0.05, interval_s=0.0,
            durable_dir=d, checkpoint_every=1, recorder=rec, seed=9)
        node.add(1, 2)
        sup.run(max_rounds=6)       # several checkpoint generations land
        node.add(3)                 # WAL-tail only
        node.wal.close()
        node.close()                # SIGKILL analogue

        # corrupt the NEWEST generation: recovery must fall back, not
        # die.  bit_flip_array pins the flip inside a member's data
        # region (a blind flip can land in benign zip framing)
        store = CheckpointStore(d)
        newest = store.path_for(store.latest_generation())
        StorageFaults(seed=2).bit_flip_array(newest)

        rec2 = Recorder()
        sup2 = SyncSupervisor.restore_durable(
            d, [lossy_addr], recorder=rec2,
            policy=BackoffPolicy(base_s=0.005, cap_s=0.05, max_retries=3),
            sync_timeout_s=2.0, breaker_threshold=5,
            breaker_cooldown_s=0.05, interval_s=0.0,
            checkpoint_every=2, seed=10)
        snap = rec2.snapshot()
        assert snap["counters"]["restore.fallbacks"] >= 1
        assert snap["gauges"]["restore.generation"] < \
            store.latest_generation()
        # local adds survived (checkpoint K-1 + WAL replay covers them:
        # the WAL is only truncated on a SUCCESSFUL newer checkpoint)
        got = set(int(e) for e in sup2.node.members())
        assert {1, 2}.issubset(got)

        expect = {1, 2, 3, 20, 21, 22}
        sup2.run(max_rounds=60, until=lambda: set(
            int(e) for e in sup2.node.members()) == expect)
        assert set(int(e) for e in sup2.node.members()) == expect
        # and the peer learned the restored node's elements back
        for _ in range(60):
            if {1, 2}.issubset(set(int(e) for e in peer.members())):
                break
            sup2.sync_round()
        assert {1, 2}.issubset(set(int(e) for e in peer.members()))
        sup2.node.wal.close()
        sup2.node.close()
    finally:
        proxy.close()
        peer.close()


def test_regressed_restore_forces_full_resync_and_heals_vv_hole(tmp_path):
    """Pins the replay-context wedge: a WAL record logged against a
    NEWER generation carries a src_vv that fast-forwards a regressed
    base past lanes only delivered in already-truncated records.  Delta
    compression then hides the hole forever (the peer compresses
    against our covering vv).  The regressed restore must enter the
    forced-FULL healing epoch — persisted in ``resync-pending`` so a
    re-kill before the heal cannot bake the hole into a checkpoint —
    and one supervisor pass over the peer set must heal and retire it."""
    from go_crdt_playground_tpu.net import (Node, StorageFaults,
                                            SyncSupervisor)
    from go_crdt_playground_tpu.utils.wal import DeltaWal

    d = str(tmp_path / "durable")
    peer = Node(1, 32, 2, recorder=Recorder(), conn_timeout_s=5.0,
                hello_timeout_s=0.5)
    peer_addr = peer.serve()
    peer.add(20, 21, 22)
    try:
        rec = Recorder()
        node = Node(0, 32, 2, recorder=rec,
                    wal=DeltaWal(os.path.join(d, "wal"), recorder=rec))
        store = CheckpointStore(d, recorder=rec)
        node.save_durable(store)            # gen1: knows nothing of peer
        node.sync_with(peer_addr)           # learns 20-22 (WAL record A)
        node.save_durable(store)            # gen2 bakes them in; WAL cut
        peer.add(23)
        node.sync_with(peer_addr)           # δ{23}, src_vv[1]=4 (record B)
        assert set(int(e) for e in node.members()) == {20, 21, 22, 23}
        node.wal.close()                    # SIGKILL analogue

        StorageFaults(seed=3).bit_flip_array(store.path_for(2))

        rec2 = Recorder()
        back = Node.restore_durable(d, recorder=rec2)
        # the replay GUARD must refuse record B on the regressed gen1
        # base (its δ-compression assumed vv[1]=3): without the guard,
        # replay would fast-forward vv[1] to 4 while delivering only
        # element 23 — a hole no later delta OR full merge can fill
        # (full merge reads covered-but-absent as an observed remove)
        assert back.generation == 1
        assert int(back.vv()[1]) == 0, "guard must refuse the future record"
        assert list(back.members()) == []
        snap2 = rec2.snapshot()["counters"]
        assert snap2["wal.future_records"] == 1
        assert snap2["restore.fallbacks"] >= 1
        # regressed restore arms the belt-and-braces healing epoch too
        assert back.full_resync_pending
        assert os.path.exists(os.path.join(d, "resync-pending"))
        assert snap2["restore.full_resync"] == 1

        sup = SyncSupervisor(back, [peer_addr], interval_s=0.0,
                             sync_timeout_s=2.0, recorder=rec2, seed=1,
                             durable_dir=d)
        sup.sync_round()
        assert set(int(e) for e in back.members()) == {20, 21, 22, 23}
        assert int(back.vv()[1]) == 4
        assert not back.full_resync_pending
        assert not os.path.exists(os.path.join(d, "resync-pending"))
        back.wal.close()
        back.close()
    finally:
        peer.close()


def test_resync_pending_flag_survives_rekill(tmp_path):
    """A second kill BEFORE the heal completes must resume the healing
    epoch from the persisted flag, even though the second restore itself
    did not regress."""
    from go_crdt_playground_tpu.net import Node, StorageFaults
    from go_crdt_playground_tpu.utils.wal import DeltaWal

    d = str(tmp_path / "durable")
    rec = Recorder()
    node = Node(0, 16, 2, recorder=rec,
                wal=DeltaWal(os.path.join(d, "wal"), recorder=rec))
    store = CheckpointStore(d, recorder=rec)
    node.add(1)
    node.save_durable(store)
    node.add(2)
    node.save_durable(store)
    node.wal.close()
    StorageFaults(seed=4).bit_flip_array(store.path_for(2))

    back = Node.restore_durable(d, recorder=Recorder())
    assert back.full_resync_pending      # regressed: gen1 < gen2 on disk
    back.wal.close()                     # re-kill before any heal

    again = Node.restore_durable(d, recorder=Recorder())
    # this restore also falls back (gen2 is still corrupt), but even on
    # a non-regressed restore the persisted flag must keep the epoch on
    assert again.full_resync_pending
    again.clear_full_resync()
    assert not os.path.exists(os.path.join(d, "resync-pending"))
    again.wal.close()

    third = Node.restore_durable(d, recorder=Recorder())
    # flag cleared and gen2 still corrupt -> still regressed -> re-armed
    assert third.full_resync_pending
    third.wal.close()


def test_restore_durable_all_corrupt_uses_fallback_init(tmp_path):
    from go_crdt_playground_tpu.net import Node
    from go_crdt_playground_tpu.utils.wal import DeltaWal

    d = str(tmp_path / "durable")
    rec = Recorder()
    node = Node(0, 16, 2, recorder=rec,
                wal=DeltaWal(os.path.join(d, "wal"), recorder=rec))
    store = CheckpointStore(d, recorder=rec)
    node.add(1)
    node.save_durable(store)
    node.add(2)                     # survives in the WAL tail
    node.wal.close()
    _flip_bit(store.path_for(1))    # the ONLY generation is corrupt

    with pytest.raises(CheckpointCorrupt):
        Node.restore_durable(d, recorder=Recorder())
    rec2 = Recorder()
    back = Node.restore_durable(
        d, recorder=rec2,
        fallback_init=lambda: Node(0, 16, 2))
    # every generation is gone and the WAL tail was compressed against
    # the destroyed context, so the replay guard refuses it (applying
    # it would poison the fresh vv); recovery proceeds empty with the
    # forced-FULL healing epoch armed — anti-entropy re-ships history
    assert list(back.members()) == []
    snap = rec2.snapshot()["counters"]
    assert snap["wal.future_records"] >= 1
    assert back.full_resync_pending
    back.wal.close()


def test_partial_replay_resets_wal_so_second_kill_keeps_new_acks(tmp_path):
    """After a guard-refused replay the WAL must be reset: otherwise
    post-restore acked records land BEHIND the permanently-refused
    suffix and a second kill silently discards them (review finding)."""
    from go_crdt_playground_tpu.net import Node, StorageFaults
    from go_crdt_playground_tpu.utils.wal import DeltaWal

    d = str(tmp_path / "durable")
    peer = Node(1, 32, 2, recorder=Recorder(), conn_timeout_s=5.0,
                hello_timeout_s=0.5)
    peer_addr = peer.serve()
    peer.add(20)
    try:
        rec = Recorder()
        node = Node(0, 32, 2, recorder=rec,
                    wal=DeltaWal(os.path.join(d, "wal"), recorder=rec))
        store = CheckpointStore(d, recorder=rec)
        node.save_durable(store)        # gen1
        node.sync_with(peer_addr)       # record A
        node.save_durable(store)        # gen2; WAL reset
        peer.add(21)
        node.sync_with(peer_addr)       # record B (context: gen2)
        node.wal.close()
        StorageFaults(seed=5).bit_flip_array(store.path_for(2))

        back = Node.restore_durable(d, recorder=Recorder())
        # replay refused record B on the gen1 base and RESET the log
        assert back.wal.record_count() == 0
        back.add(7)                     # acked post-restore, WAL'd
        back.wal.close()                # second kill, still no checkpoint

        rec3 = Recorder()
        again = Node.restore_durable(d, recorder=rec3)
        assert 7 in set(int(e) for e in again.members()), \
            "second kill must not lose the post-restore acked add"
        assert rec3.snapshot()["counters"]["wal.records"] >= 1
        again.wal.close()
    finally:
        peer.close()


def test_save_durable_seals_then_drops_only_covered_records(tmp_path):
    """save_durable's two-phase truncation: records appended AFTER the
    snapshot/seal survive the checkpoint's segment drop."""
    from go_crdt_playground_tpu.net import Node
    from go_crdt_playground_tpu.utils.checkpoint import save_checkpoint
    from go_crdt_playground_tpu.utils.wal import DeltaWal

    d = str(tmp_path / "durable")
    rec = Recorder()
    node = Node(0, 16, 2, recorder=rec,
                wal=DeltaWal(os.path.join(d, "wal"), recorder=rec))
    node.add(1)

    class SlowStore(CheckpointStore):
        # a mutation racing the (out-of-lock) dump: it must land in the
        # fresh post-seal segment and survive the drop
        def save(self, state, **kw):
            node.add(2)
            return super().save(state, **kw)

    store = SlowStore(d, recorder=rec)
    gen = node.save_durable(store)
    assert gen == 1
    assert node.wal.record_count() == 1, \
        "the racing add's record must survive the checkpoint truncation"
    node.wal.close()

    back = Node.restore_durable(d, recorder=Recorder())
    assert set(int(e) for e in back.members()) == {1, 2}
    back.wal.close()


def test_records_scan_counts_one_tear_once(tmp_path):
    from go_crdt_playground_tpu.utils.wal import DeltaWal

    p = str(tmp_path / "wal")
    rec = Recorder()
    with DeltaWal(p, recorder=rec) as w:
        for i in range(4):
            w.append(b"x" * 20)
        seg = sorted(os.listdir(p))[-1]
        with open(os.path.join(p, seg), "r+b") as f:
            f.truncate(os.path.getsize(os.path.join(p, seg)) - 3)
        w.record_count()
        list(w.records())
        list(w.records())
    assert rec.snapshot()["counters"]["wal.torn_tail"] == 1, \
        "one physical tear must count once, not once per scan"


def test_wal_alone_recovers_pre_first_checkpoint_history(tmp_path):
    """Died-before-first-checkpoint: the store is empty but the WAL
    holds the entire history from birth, whose guards chain from zero —
    replay alone reconstructs the state."""
    from go_crdt_playground_tpu.net import Node
    from go_crdt_playground_tpu.utils.wal import DeltaWal

    d = str(tmp_path / "durable")
    rec = Recorder()
    node = Node(0, 16, 2, recorder=rec,
                wal=DeltaWal(os.path.join(d, "wal"), recorder=rec))
    node.add(1, 2)
    node.delete(1)
    node.add(3)
    node.wal.close()                    # killed before any save_durable

    rec2 = Recorder()
    back = Node.restore_durable(
        d, recorder=rec2, fallback_init=lambda: Node(0, 16, 2))
    assert set(int(e) for e in back.members()) == {2, 3}
    snap = rec2.snapshot()["counters"]
    assert snap["wal.records"] == 3
    assert "wal.future_records" not in snap
    assert not back.full_resync_pending  # nothing regressed
    back.wal.close()


# -- compact WAL records (serve-path throughput ladder) ----------------------


def _fields_equal(a, b):
    for name in a._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, name)), np.asarray(getattr(b, name)),
            err_msg=name)


def test_dense_only_store_replays_through_new_reader(tmp_path):
    """Backward compatibility: a store written ENTIRELY with the legacy
    dense records (a pre-ladder node: compact records off) replays
    through the upgraded reader to the same state."""
    from go_crdt_playground_tpu.net import Node
    from go_crdt_playground_tpu.utils.wal import DeltaWal

    d = str(tmp_path / "durable")
    rec = Recorder()
    node = Node(0, 48, 3, recorder=rec, wal_compact_records=False,
                wal=DeltaWal(os.path.join(d, "wal"), recorder=rec))
    node.add(1, 2, 3)
    node.delete(2)
    node.ingest_batch(
        np.eye(48, dtype=bool)[[5, 9]], np.zeros((2, 48), bool))
    node.wal.close()
    snap = rec.snapshot()["counters"]
    assert snap["wal.dense_records"] == 3
    assert "wal.compact_records" not in snap

    rec2 = Recorder()
    back = Node.restore_durable(
        d, recorder=rec2, fallback_init=lambda: Node(0, 48, 3))
    _fields_equal(back.state_slice(), node.state_slice())
    snap2 = rec2.snapshot()["counters"]
    assert snap2["wal.replayed_dense"] == 3
    assert "wal.replayed_compact" not in snap2
    back.wal.close()


def test_mixed_dense_compact_segment_replays_in_order(tmp_path):
    """A segment interleaving dense and compact records — local compact
    δs, a dense overflow-style record, an applied peer payload (always
    dense), compact again — replays in order to the writer's state,
    with both mode counters accounted."""
    from go_crdt_playground_tpu.net import Node
    from go_crdt_playground_tpu.utils.wal import DeltaWal

    d = str(tmp_path / "durable")
    rec = Recorder()
    node = Node(0, 48, 3, recorder=rec,
                wal=DeltaWal(os.path.join(d, "wal"), recorder=rec))
    node.add(1, 2)                       # compact
    with node._lock:                     # force one DENSE local record
        node.wal_compact_records = False
    node.add(7)                          # dense
    with node._lock:
        node.wal_compact_records = True
    node.delete(2)                       # compact (deletion lanes)
    # an applied peer payload is logged dense as-received
    peer = Node(1, 48, 3)
    peer.add(30, 31)
    import jax

    me_vv = node.vv()
    from go_crdt_playground_tpu.net import framing as fr
    from go_crdt_playground_tpu.ops import delta as delta_ops

    prow = jax.tree.map(lambda x: x[0], peer._state)
    payload = delta_ops.delta_extract(prow, np.zeros(3, np.uint32))
    body = fr.encode_payload_msg(fr.MODE_FULL, 1,
                                 np.asarray(prow.processed), payload)
    node.apply_payload_body(body)        # dense (wire body)
    node.ingest_batch(np.eye(48, dtype=bool)[[40]],
                      np.zeros((1, 48), bool))  # compact (fused batch)
    node.wal.close()
    snap = rec.snapshot()["counters"]
    assert snap["wal.compact_records"] == 3
    assert snap["wal.dense_records"] == 2
    assert me_vv is not None

    rec2 = Recorder()
    back = Node.restore_durable(
        d, recorder=rec2, fallback_init=lambda: Node(0, 48, 3))
    _fields_equal(back.state_slice(), node.state_slice())
    snap2 = rec2.snapshot()["counters"]
    assert snap2["wal.records"] == 5
    assert snap2["wal.replayed_compact"] == 3
    assert snap2["wal.replayed_dense"] == 2
    back.wal.close()


def test_compact_record_respects_causal_replay_guard(tmp_path):
    """The causal guard survives the record-format change: a compact
    record whose guard vv outruns the replaying base is refused
    (wal.future_records) exactly like a dense one, the refused suffix
    is discarded (prefix rule), and the log resets."""
    from go_crdt_playground_tpu.net import Node
    from go_crdt_playground_tpu.utils import wire
    from go_crdt_playground_tpu.utils.wal import DeltaWal

    d = str(tmp_path / "durable")
    os.makedirs(d)
    wal = DeltaWal(os.path.join(d, "wal"))
    # record 1: applies from a zero base (guard 0) — lane 3 added
    wal.append(wire.encode_compact_wal_body(
        np.zeros(2, np.uint32), 0, np.asarray([1, 0], np.uint32),
        np.asarray([1, 0], np.uint32), [3], [0], [1], [], [], [], 16))
    # record 2: guard claims vv [5, 0] — a future the base never saw
    wal.append(wire.encode_compact_wal_body(
        np.asarray([5, 0], np.uint32), 0,
        np.asarray([6, 0], np.uint32), np.asarray([6, 0], np.uint32),
        [9], [0], [6], [], [], [], 16))
    wal.close()

    rec = Recorder()
    back = Node.restore_durable(
        d, recorder=rec, fallback_init=lambda: Node(0, 16, 2))
    assert [int(e) for e in back.members()] == [3]  # prefix applied
    snap = rec.snapshot()["counters"]
    assert snap["wal.records"] == 1
    assert snap["wal.future_records"] == 1
    assert back.full_resync_pending      # regressed base arms the heal
    assert back.wal.record_count() == 0  # refused suffix reset
    back.wal.close()


def test_compact_and_dense_records_replay_to_identical_state(tmp_path):
    """The same op stream logged compact vs dense recovers to the same
    state — the record form is an encoding, never a semantics."""
    from go_crdt_playground_tpu.net import Node
    from go_crdt_playground_tpu.utils.wal import DeltaWal

    stores = {}
    for mode, compact in (("compact", True), ("dense", False)):
        d = str(tmp_path / mode)
        node = Node(0, 48, 3, wal_compact_records=compact,
                    wal=DeltaWal(os.path.join(d, "wal")))
        add = np.zeros((3, 48), bool)
        add[0, [1, 5]] = True
        add[1, 9] = True
        dl = np.zeros((3, 48), bool)
        dl[2, 5] = True
        node.ingest_batch(add, dl)
        node.add(20)
        node.delete(9)
        node.wal.close()
        stores[mode] = (d, node)
    backs = {}
    for mode, (d, _) in stores.items():
        back = Node.restore_durable(
            d, fallback_init=lambda: Node(0, 48, 3))
        backs[mode] = back.state_slice()
        back.wal.close()
    _fields_equal(backs["compact"], backs["dense"])
    _fields_equal(backs["compact"], stores["compact"][1].state_slice())


def test_compact_record_refuses_universe_change(tmp_path):
    """Review fix: compact records embed E like the dense form's masked
    sections — a store reopened at a different universe must FAIL
    decode (bad-record prefix rule), never merge in-range lane ids
    onto the wrong lanes."""
    from go_crdt_playground_tpu.net import Node
    from go_crdt_playground_tpu.utils import wire
    from go_crdt_playground_tpu.utils.wal import DeltaWal

    d = str(tmp_path / "durable")
    os.makedirs(d)
    wal = DeltaWal(os.path.join(d, "wal"))
    wal.append(wire.encode_compact_wal_body(
        np.zeros(2, np.uint32), 0, np.asarray([1, 0], np.uint32),
        np.asarray([1, 0], np.uint32), [3], [0], [1], [], [], [], 64))
    wal.close()
    rec = Recorder()
    # replay at E=16: lane 3 is in range, but the universe differs
    back = Node.restore_durable(
        d, recorder=rec, fallback_init=lambda: Node(0, 16, 2))
    assert list(back.members()) == []
    assert rec.snapshot()["counters"]["wal.bad_records"] == 1
    back.wal.close()


def test_wal_records_filter_guard_covered_deletions(tmp_path):
    """δ-for-WAL deletion-log filtering (DESIGN.md §16): a record's
    deleted section carries ONLY the deletions its own window
    produced — lanes whose dots the replay guard (pre-op vv) covers
    were introduced by earlier records and are filtered, so records
    are O(changed) even against a large standing deletion log — and
    replay still recovers the writer's exact state."""
    from go_crdt_playground_tpu.net import Node
    from go_crdt_playground_tpu.utils import wire
    from go_crdt_playground_tpu.utils.wal import DeltaWal

    d = str(tmp_path / "durable")
    rec = Recorder()
    node = Node(0, 48, 3, recorder=rec,
                wal=DeltaWal(os.path.join(d, "wal"), recorder=rec))
    node.add(*range(20))
    node.delete(*range(10))      # standing deletion log: 10 records
    # an unrelated batch: its record must carry ZERO deletion lanes
    node.ingest_batch(np.eye(48, dtype=bool)[[30, 31]],
                      np.zeros((2, 48), bool))
    # a batch with ONE fresh delete: exactly that lane, not the log
    node.ingest_batch(np.zeros((1, 48), bool),
                      np.eye(48, dtype=bool)[[15]])
    bodies = list(node.wal.records())
    assert len(bodies) == 4

    def record_payload(body):
        from go_crdt_playground_tpu.net import framing as fr

        if body[:1] == bytes((wire.WAL_COMPACT_TAG,)):
            return wire.decode_compact_wal_body(body, 48, 3)[1]
        _, pos = wire._decode_vv_py(body, 0, 3)
        return fr.decode_payload_msg(body[pos:], 48, 3)[1]

    payloads = [record_payload(b) for b in bodies]
    # record 2 (the delete op) carries its own 10 fresh deletions
    assert int(np.asarray(payloads[1].deleted).sum()) == 10
    # record 3 (adds only): zero deletion lanes despite the log —
    # pre-filter it re-carried all 10, forcing dense; filtered it
    # fits the compact form
    assert bodies[2][:1] == bytes((wire.WAL_COMPACT_TAG,))
    assert int(np.asarray(payloads[2].deleted).sum()) == 0
    # record 4: exactly the one fresh deletion
    dl = np.nonzero(np.asarray(payloads[3].deleted))[0]
    assert dl.tolist() == [15]

    node.wal.close()
    back = Node.restore_durable(d, fallback_init=lambda: Node(0, 48, 3))
    _fields_equal(back.state_slice(), node.state_slice())
    back.wal.close()


def test_dense_fallback_record_filters_deletions_too(tmp_path):
    """The filter is the record CONTRACT, not a compact-form detail:
    an oversized δ that falls back to the dense record form still
    drops guard-covered deletion lanes, and replays to state
    identity."""
    from go_crdt_playground_tpu.net import Node
    from go_crdt_playground_tpu.net.framing import encode_delta_wal_record
    from go_crdt_playground_tpu.utils.wal import DeltaWal

    E = 48
    d = str(tmp_path / "durable")
    node = Node(0, E, 3, wal=DeltaWal(os.path.join(d, "wal")))
    node.add(*range(24))
    node.delete(*range(12))
    # a batch touching MOST lanes: past the compact break-even, so the
    # record goes dense — count its encoded deletion section
    add = np.zeros((1, E), bool)
    add[0, 24:48] = True
    pre_vv = node.vv()
    node.ingest_batch(add, np.zeros((1, E), bool))
    bodies = list(node.wal.records())
    from go_crdt_playground_tpu.utils import wire as w

    last = bodies[-1]
    assert last[:1] != bytes((w.WAL_COMPACT_TAG,)), "expected dense"
    # decode: guard vv || PAYLOAD body
    guard, pos = w._decode_vv_py(last, 0, 3)
    np.testing.assert_array_equal(guard, pre_vv)
    from go_crdt_playground_tpu.net import framing as fr

    mode, payload = fr.decode_payload_msg(last[pos:], E, 3)
    assert int(np.asarray(payload.deleted).sum()) == 0  # all filtered
    assert int(np.asarray(payload.changed).sum()) == 24
    node.wal.close()
    back = Node.restore_durable(d, fallback_init=lambda: Node(0, E, 3))
    _fields_equal(back.state_slice(), node.state_slice())
    back.wal.close()
    # and the shared policy itself, called directly with a fresh
    # deletion mixed into an old log, keeps exactly the fresh lane
    import jax

    me = jax.tree.map(lambda x: x[0], node._state)
    from go_crdt_playground_tpu.ops import delta as delta_ops
    import jax.numpy as jnp

    p = delta_ops.delta_extract(me, jnp.zeros(3, jnp.uint32))
    body, is_compact = encode_delta_wal_record(
        np.zeros(3, np.uint32), 0, p, None)
    # zero guard: NOTHING is covered — every deletion survives
    # (whichever record form the break-even picked)
    if is_compact:
        _, p2 = w.decode_compact_wal_body(body, E, 3)
    else:
        g, pos = w._decode_vv_py(body, 0, 3)
        _, p2 = fr.decode_payload_msg(body[pos:], E, 3)
    assert int(np.asarray(p2.deleted).sum()) == \
        int(np.asarray(p.deleted).sum())
