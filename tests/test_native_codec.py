"""Native C++ codec: parity with the pure-Python implementations.

The native path (go_crdt_playground_tpu/native) must be observably
identical to utils.codec.ElementDict and byte-identical to the Python
wire codec — these tests pin both.  If no C++ toolchain is available
the native tests skip (the framework contract is graceful fallback).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from go_crdt_playground_tpu import native
from go_crdt_playground_tpu.models import awset_delta
from go_crdt_playground_tpu.ops import delta as delta_ops
from go_crdt_playground_tpu.utils import wire
from go_crdt_playground_tpu.utils.codec import ElementDict

needs_native = pytest.mark.skipif(
    not native.available(),
    reason=f"native codec unavailable: {native.build_error()}")


# ---------------------------------------------------------------------------
# Element dictionary parity
# ---------------------------------------------------------------------------


@needs_native
def test_element_dict_parity_basic():
    py = ElementDict(capacity=8)
    nat = native.NativeElementDict(capacity=8)
    words = ["Anne", "Bob", "Anne", "Cat", "", "Ünïcode✓", "Bob"]
    assert py.encode_many(words) == nat.encode_many(words)
    assert len(py) == len(nat)
    assert py.capacity == nat.capacity
    for w in words + ["missing"]:
        assert (w in py) == (w in nat)
    ids = list(range(len(py)))
    assert [py.decode(i) for i in ids] == nat.decode_many(ids)
    assert py.state_dict() == nat.state_dict()


@needs_native
def test_element_dict_overflow_matches():
    py = ElementDict(capacity=2)
    nat = native.NativeElementDict(capacity=2)
    for d in (py, nat):
        d.encode("a")
        d.encode("b")
        with pytest.raises(OverflowError):
            d.encode("c")
        d.grow()
        assert d.encode("c") == 2
    assert py.state_dict() == nat.state_dict()


@needs_native
def test_element_dict_partial_overflow_batch_prefix_interned():
    """On mid-batch overflow both implementations keep the prefix."""
    py = ElementDict(capacity=2)
    nat = native.NativeElementDict(capacity=2)
    with pytest.raises(OverflowError):
        py.encode_many(["x", "y", "z"])
    with pytest.raises(OverflowError):
        nat.encode_many(["x", "y", "z"])
    assert py.state_dict() == nat.state_dict()
    assert len(nat) == 2


@needs_native
def test_native_roundtrip_from_state_dict():
    nat = native.NativeElementDict(capacity=16, values=["p", "q", "r"])
    clone = native.NativeElementDict.from_state_dict(nat.state_dict())
    assert clone.state_dict() == nat.state_dict()


def test_factory_falls_back():
    d = native.make_element_dict(capacity=4, prefer_native=False)
    assert isinstance(d, ElementDict)
    d2 = native.make_element_dict(capacity=4)
    assert d2.encode("k") == 0


# ---------------------------------------------------------------------------
# Wire codec
# ---------------------------------------------------------------------------


def _payload(rng, e=40, a=5):
    st = awset_delta.init(1, e, a)
    present = rng.random(e) < 0.3
    deleted = ~present & (rng.random(e) < 0.2)
    st = st._replace(
        vv=jnp.asarray(rng.integers(0, 9, (1, a)), jnp.uint32),
        present=jnp.asarray(present)[None],
        dot_actor=jnp.asarray(
            np.where(present, rng.integers(0, a, e), 0), jnp.uint32)[None],
        dot_counter=jnp.asarray(
            np.where(present, rng.integers(1, 9, e), 0), jnp.uint32)[None],
        deleted=jnp.asarray(deleted)[None],
        del_dot_actor=jnp.asarray(
            np.where(deleted, rng.integers(0, a, e), 0), jnp.uint32)[None],
        del_dot_counter=jnp.asarray(
            np.where(deleted, rng.integers(1, 9, e), 0), jnp.uint32)[None],
    )
    row = __import__("jax").tree.map(lambda x: x[0], st)
    dst_vv = jnp.asarray(rng.integers(0, 5, a), jnp.uint32)
    return delta_ops.delta_extract(row, dst_vv)


@pytest.mark.parametrize("prefer_native", [False, True])
def test_wire_roundtrip(prefer_native):
    if prefer_native and not native.available():
        pytest.skip("no native codec")
    rng = np.random.default_rng(1)
    for _ in range(5):
        p = _payload(rng)
        buf = wire.encode_payload(p, prefer_native=prefer_native)
        q = wire.decode_payload(buf, 40, 5, src_actor=int(p.src_actor),
                                prefer_native=prefer_native)
        for name in ("src_vv", "changed", "ch_da", "ch_dc", "deleted",
                     "del_da", "del_dc"):
            np.testing.assert_array_equal(
                np.asarray(getattr(p, name)), np.asarray(getattr(q, name)),
                err_msg=name)


@needs_native
def test_wire_native_and_python_byte_identical():
    rng = np.random.default_rng(2)
    for _ in range(5):
        p = _payload(rng, e=130, a=7)
        assert (wire.encode_payload(p, prefer_native=True)
                == wire.encode_payload(p, prefer_native=False))


@needs_native
def test_wire_cross_decoding():
    """Bytes from either implementation decode in the other."""
    rng = np.random.default_rng(3)
    p = _payload(rng)
    b_native = wire.encode_payload(p, prefer_native=True)
    q = wire.decode_payload(b_native, 40, 5, prefer_native=False)
    np.testing.assert_array_equal(np.asarray(p.changed),
                                  np.asarray(q.changed))
    b_py = wire.encode_payload(p, prefer_native=False)
    q2 = wire.decode_payload(b_py, 40, 5, prefer_native=True)
    np.testing.assert_array_equal(np.asarray(p.ch_dc), np.asarray(q2.ch_dc))


def test_wire_compression_vs_dense():
    """A sparse payload's wire form is much smaller than its dense form."""
    rng = np.random.default_rng(4)
    p = _payload(rng, e=1024, a=8)
    dense = p.nbytes_dense()
    compact = wire.payload_nbytes_wire(p)
    assert compact < dense / 4


def test_wire_rejects_malformed():
    rng = np.random.default_rng(5)
    p = _payload(rng)
    buf = wire.encode_payload(p, prefer_native=False)
    with pytest.raises(ValueError):
        wire.decode_payload(buf + b"\x00", 40, 5, prefer_native=False)
    with pytest.raises(ValueError):
        wire.decode_payload(buf[:-1], 40, 5, prefer_native=False)
    with pytest.raises(ValueError):
        wire.decode_payload(buf, 41, 5, prefer_native=False)
