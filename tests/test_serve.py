"""Op-ingest serving frontend (serve/): protocol, admission, batching,
durability, deadlines, drain (DESIGN.md §16).

The load-shape tests (shed curves, SIGKILL windows) live in the slow
serve soak (tests/test_serve_soak.py); here every behavior is pinned
DETERMINISTICALLY — the batcher is gated where a test needs the queue
to back up, so no assertion depends on thread timing races.
"""

import os
import threading
import time

import numpy as np
import pytest

from go_crdt_playground_tpu.net.framing import ProtocolError
from go_crdt_playground_tpu.serve import protocol
from go_crdt_playground_tpu.serve.admission import AdmissionQueue, OpRequest
from go_crdt_playground_tpu.serve.client import ServeClient
from go_crdt_playground_tpu.serve.frontend import ServeFrontend


# ---------------------------------------------------------------------------
# protocol bodies
# ---------------------------------------------------------------------------


def test_protocol_op_roundtrip():
    body = protocol.encode_op(7, protocol.OP_ADD, [1, 5, 300],
                              deadline_us=2_000_000)
    assert protocol.decode_op(body) == (7, protocol.OP_ADD, [1, 5, 300],
                                        2_000_000)
    body = protocol.encode_op(1, protocol.OP_DEL, [0])
    assert protocol.decode_op(body) == (1, protocol.OP_DEL, [0], 0)


def test_protocol_op_rejects_malformed():
    with pytest.raises(ValueError):
        protocol.encode_op(1, 9, [1])  # unknown kind
    with pytest.raises(ValueError):
        protocol.encode_op(1, protocol.OP_ADD, [])  # empty key set
    good = protocol.encode_op(3, protocol.OP_ADD, [1, 2])
    with pytest.raises(ProtocolError):
        protocol.decode_op(good + b"\x00")  # trailing bytes
    with pytest.raises(ProtocolError):
        protocol.decode_op(good[:-1])  # truncated
    with pytest.raises(ProtocolError):
        protocol.decode_op(b"")


def test_protocol_ack_reject_members_roundtrip():
    assert protocol.decode_ack(protocol.encode_ack(42)) == 42
    body = protocol.encode_reject(9, protocol.REJECT_OVERLOADED, "full")
    assert protocol.decode_reject(body) == (9, protocol.REJECT_OVERLOADED,
                                            "full")
    with pytest.raises(ValueError):
        protocol.encode_reject(1, 99, "?")
    req, members, vv = protocol.decode_members(
        protocol.encode_members(5, [1, 2, 9], np.asarray([3, 0, 7])))
    assert (req, members, vv.tolist()) == (5, [1, 2, 9], [3, 0, 7])
    # every reject code maps to a typed exception, and back (the
    # router's relay direction re-encodes the downstream verdict)
    assert set(protocol.REJECT_EXCEPTIONS) == {
        protocol.REJECT_OVERLOADED, protocol.REJECT_EXPIRED,
        protocol.REJECT_DRAINING, protocol.REJECT_INVALID,
        protocol.REJECT_UNAVAILABLE, protocol.REJECT_MOVING,
        protocol.REJECT_STALE_EPOCH, protocol.REJECT_STORAGE,
        protocol.REJECT_STALE_SHARD_EPOCH}
    for code, exc in protocol.REJECT_EXCEPTIONS.items():
        assert protocol.REJECT_CODES[exc] == code


# ---------------------------------------------------------------------------
# admission queue (no sockets)
# ---------------------------------------------------------------------------


def _req(i: int) -> OpRequest:
    return OpRequest(i, protocol.OP_ADD, [i], None, None, 0.0)


def test_admission_queue_bounds_and_sheds():
    q = AdmissionQueue(2)
    assert q.offer(_req(1)) and q.offer(_req(2))
    assert not q.offer(_req(3))  # at depth: shed, never queue
    assert q.depth() == 2
    batch = q.take_batch(10, wait_s=0.0, flush_s=0.0)
    assert [r.req_id for r in batch] == [1, 2]
    assert q.offer(_req(4))  # drained: admits again


def test_admission_queue_size_watermark():
    q = AdmissionQueue(16)
    for i in range(5):
        q.offer(_req(i))
    # size watermark fires before the flush timer: 3 now, 2 next
    assert len(q.take_batch(3, wait_s=0.0, flush_s=10.0)) == 3
    assert len(q.take_batch(3, wait_s=0.0, flush_s=0.0)) == 2


def test_admission_queue_time_watermark_gathers_late_arrivals():
    q = AdmissionQueue(16)
    q.offer(_req(0))
    t = threading.Thread(
        target=lambda: (time.sleep(0.05), q.offer(_req(1))), daemon=True)
    t.start()
    batch = q.take_batch(8, wait_s=1.0, flush_s=1.0)
    t.join()
    # the flush window kept the batch open long enough to coalesce both
    assert [r.req_id for r in batch] == [0, 1]


def test_admission_queue_close_drains_then_refuses():
    q = AdmissionQueue(4)
    q.offer(_req(1))
    q.close()
    assert not q.offer(_req(2))  # closed: refuse new
    assert [r.req_id for r in q.take_batch(4, 0.0, 0.0)] == [1]  # drain old
    assert q.take_batch(4, wait_s=5.0, flush_s=0.0) == []  # no hang


# ---------------------------------------------------------------------------
# end-to-end frontend (in-process, deterministic)
# ---------------------------------------------------------------------------

E, A = 64, 2


@pytest.fixture()
def frontend(tmp_path):
    fe = ServeFrontend(E, A, durable_dir=str(tmp_path / "n0"),
                       max_batch=8, flush_ms=1.0, queue_depth=16)
    fe.serve()
    yield fe
    fe.close()


def _addr(fe):
    return fe.addr


def test_ingest_end_to_end_and_query(frontend):
    with ServeClient(_addr(frontend)) as c:
        c.add(1, 2, 3)
        c.add(5)
        c.delete(2)
        members, vv = c.members()
    assert members == [1, 3, 5]
    assert vv[0] == 5  # 4 add ticks + 1 del tick, actor 0
    snap = frontend.recorder.snapshot()
    assert snap["counters"]["serve.ops.acked"] == 3
    assert snap["counters"]["serve.ops.admitted"] == 3
    lat = snap["observations"]["serve.ingest_latency_s"]
    assert lat["n"] == 3 and 0 < lat["p50"] <= lat["p99"]
    assert snap["observations"]["serve.batch.occupancy"]["n"] >= 1


def test_ingest_batch_matches_sequential_ops(tmp_path):
    """The packed (B, E) batch apply is bitwise-identical to the same
    requests through the host-driven per-op path (the ops/ingest.py
    conformance pin), exercised END-TO-END through the wire."""
    import jax

    from go_crdt_playground_tpu.net.peer import Node

    fe = ServeFrontend(E, A, durable_dir=str(tmp_path / "n0"),
                       max_batch=4, flush_ms=0.5)
    fe.serve()
    try:
        with ServeClient(_addr(fe)) as c:
            c.add(3, 9, 11)
            c.delete(9)
            c.add(9, 20)
            c.delete(3, 20)
        got = fe.node.state_slice()
    finally:
        fe.close()
    ref = Node(0, E, A)
    ref.add(3, 9, 11)
    ref.delete(9)
    ref.add(9, 20)
    ref.delete(3, 20)
    want = ref.state_slice()
    for name in want._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(got, name)), np.asarray(getattr(want, name)),
            err_msg=name)
    assert jax is not None


def test_invalid_element_is_typed_reject(frontend):
    with ServeClient(_addr(frontend)) as c:
        with pytest.raises(protocol.InvalidOp):
            c.add(E + 5)
        c.add(1)  # the connection survives an invalid op
    assert frontend.recorder.snapshot()["counters"][
        "serve.rejects.invalid"] == 1


def test_duplicate_elements_refused_both_ends(frontend):
    """Review fix: an OP body is a key SET — duplicates would apply
    set-wise through the packed batch but per-argument through the
    reference host path (Node.add(7, 7) ticks the clock twice), so
    identical op streams would diverge by ingress.  The client encoder
    refuses them locally; a hand-crafted wire frame gets the typed
    per-request reject."""
    from go_crdt_playground_tpu.net import framing
    from go_crdt_playground_tpu.utils import wire

    with pytest.raises(ValueError, match="duplicate"):
        protocol.encode_op(1, protocol.OP_ADD, [7, 7])
    # wire-level: bypass the encoder's check
    body = bytearray()
    wire._put_varint(body, 5)          # req_id
    body.append(protocol.OP_ADD)
    wire._put_varint(body, 0)          # deadline
    wire._put_varint(body, 2)          # k
    wire._put_varint(body, 7)
    wire._put_varint(body, 7)
    import socket as socket_mod

    raw = socket_mod.create_connection(_addr(frontend), timeout=10.0)
    try:
        framing.send_frame(raw, protocol.MSG_OP, bytes(body))
        msg_type, reply = framing.recv_frame(raw, timeout=10.0)
        assert msg_type == protocol.MSG_REJECT
        req_id, code, reason = protocol.decode_reject(reply)
        assert (req_id, code) == (5, protocol.REJECT_INVALID)
        assert "duplicate" in reason
    finally:
        raw.close()


def test_client_fails_fast_after_reader_death(frontend):
    """Review fix: once the read loop exits (idle timeout / torn
    connection) the client flips closed — a later submit raises
    immediately instead of sending an op whose ack nothing will read."""
    c = ServeClient(_addr(frontend))
    c.add(1)
    c._sock.shutdown(2)  # tear the transport under the reader
    c._reader.join(timeout=10.0)
    assert not c._reader.is_alive()
    with pytest.raises(ConnectionError):
        c.submit_async(protocol.OP_ADD, [2])
    c.close()


def _gate_batcher(fe):
    """Block the batcher inside its next apply until the gate releases —
    the deterministic way to make the admission queue back up.
    ``gate.entered`` is set when the batcher is actually blocked inside
    the gated apply (holding its drained ops), so tests can hand-shake
    instead of guessing how many ops the first drain grabbed."""
    gate = threading.Event()
    gate.entered = threading.Event()
    inner = fe.node.ingest_batch

    def gated(*args, **kwargs):
        gate.entered.set()
        gate.wait(10.0)
        return inner(*args, **kwargs)

    fe.node.ingest_batch = gated
    return gate


def test_overload_sheds_with_typed_reply(tmp_path):
    fe = ServeFrontend(E, A, durable_dir=str(tmp_path / "n0"),
                       max_batch=1, flush_ms=0.0, queue_depth=2)
    gate = _gate_batcher(fe)
    fe.serve()
    try:
        with ServeClient(_addr(fe)) as c:
            # one op occupies the (gated) batcher, two fill the queue;
            # the fourth MUST shed with the typed Overloaded reply.
            # Hand-shake the first op into the batcher before the next
            # two: submitted back-to-back they can outrun the batcher's
            # wake-up, fill the depth-2 queue, and shed op 3 instead
            # of op 4 (the depth poll below then spins forever)
            ops = [c.submit_async(protocol.OP_ADD, [0])]
            assert gate.entered.wait(5.0)
            ops += [c.submit_async(protocol.OP_ADD, [i]) for i in (1, 2)]
            while fe.queue.depth() < 2:
                time.sleep(0.005)
            with pytest.raises(protocol.Overloaded):
                c.submit_async(protocol.OP_ADD, [7]).wait(5.0)
            gate.set()
            for op in ops:  # everything admitted still acks
                op.wait(10.0)
        snap = fe.recorder.snapshot()
        assert snap["counters"]["serve.shed.overload"] == 1
        assert snap["counters"]["serve.ops.acked"] == 3
    finally:
        gate.set()
        fe.close()


def test_deadline_propagation_sheds_expired(tmp_path):
    fe = ServeFrontend(E, A, durable_dir=str(tmp_path / "n0"),
                       max_batch=8, flush_ms=0.0, queue_depth=16)
    gate = _gate_batcher(fe)
    fe.serve()
    try:
        with ServeClient(_addr(fe)) as c:
            hold = c.submit_async(protocol.OP_ADD, [1])  # gates the batcher
            # batcher took hold -> blocked inside the gated apply (the
            # depth poll alone races: it reads 0 before hold is even
            # admitted, and a late batcher wake-up could then drain
            # hold AND doomed in one batch before the deadline passes)
            assert gate.entered.wait(5.0)
            while fe.queue.depth() > 0:
                time.sleep(0.005)
            doomed = c.submit_async(protocol.OP_ADD, [2], deadline_s=0.01)
            time.sleep(0.05)  # deadline passes while queued
            gate.set()
            with pytest.raises(protocol.DeadlineExceeded):
                doomed.wait(10.0)
            hold.wait(10.0)
            members, _ = c.members()
        assert members == [1]  # the expired op was NEVER applied
        assert fe.recorder.snapshot()["counters"]["serve.shed.expired"] == 1
    finally:
        gate.set()
        fe.close()


def test_graceful_drain_acks_admitted_ops(tmp_path):
    fe = ServeFrontend(E, A, durable_dir=str(tmp_path / "n0"),
                       max_batch=4, flush_ms=0.0, queue_depth=16)
    gate = _gate_batcher(fe)
    fe.serve()
    addr = _addr(fe)
    with ServeClient(addr) as c:
        # hand-shake the first op into the gated batcher BEFORE the
        # rest are submitted: without it the first drain may grab 2+
        # ops (reader admits faster than the batcher wakes on a busy
        # box) and the queue can never back up to 5 — the poll below
        # would spin forever
        ops = [c.submit_async(protocol.OP_ADD, [0])]
        assert gate.entered.wait(5.0)
        ops += [c.submit_async(protocol.OP_ADD, [i]) for i in range(1, 6)]
        while fe.queue.depth() < 5:  # one op is held by the gated batcher
            time.sleep(0.005)
        # drain while ops are queued: a new op gets the typed Draining
        # reject, the queued ones ack before close() returns
        closer = threading.Thread(target=fe.close, daemon=True)
        closer.start()
        while not fe.host.draining:
            time.sleep(0.005)
        with pytest.raises(protocol.Draining):
            c.submit_async(protocol.OP_ADD, [9]).wait(5.0)
        gate.set()
        closer.join(timeout=30.0)
        assert not closer.is_alive()
        for op in ops:
            op.wait(5.0)  # already resolved: close() flushed first
    snap = fe.recorder.snapshot()
    assert snap["counters"]["serve.ops.acked"] == 6
    assert snap["counters"]["serve.shed.draining"] == 1


def test_durable_ack_survives_restart(tmp_path):
    """fsync-before-ack, end to end: everything acked before an abrupt
    teardown (no final checkpoint) is recovered by restore_durable from
    the WAL alone — the §14 contract extended to the ingest path."""
    d = str(tmp_path / "n0")
    fe = ServeFrontend(E, A, durable_dir=d, max_batch=8, flush_ms=0.5)
    fe.serve()
    with ServeClient(_addr(fe)) as c:
        c.add(1, 2, 3)
        c.delete(2)
        c.add(40)
    # crash-shaped teardown: NO drain/checkpoint — the WAL is the only
    # carrier (close the open segment handle so the file is complete)
    fe.batcher.stop()
    with fe.node._lock:
        fe.node.wal.close()
    fe.node.close()
    fe2 = ServeFrontend(E, A, durable_dir=d)
    assert list(fe2.node.members()) == [1, 3, 40]
    fe2.close()


def test_frontend_disseminates_to_peers(tmp_path):
    """Ingested state rides the EXISTING anti-entropy path: a plain
    net.peer.Node peer converges to the frontend's membership."""
    from go_crdt_playground_tpu.net.peer import Node

    peer = Node(1, E, A)
    peer_addr = peer.serve("127.0.0.1", 0)
    fe = ServeFrontend(E, A, durable_dir=str(tmp_path / "n0"),
                       peers=[peer_addr], max_batch=8, flush_ms=0.5,
                       sync_interval_s=0.01)
    fe.serve()
    try:
        with ServeClient(_addr(fe)) as c:
            c.add(4, 8, 15)
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if list(peer.members()) == [4, 8, 15]:
                break
            time.sleep(0.02)
        assert list(peer.members()) == [4, 8, 15]
    finally:
        fe.close()
        peer.close()


def test_session_writer_queue_sheds_stalled_reader():
    """Serve-path ladder satellite: ``send()`` only ENQUEUES (the
    per-session writer thread owns the socket), so a client that stops
    READING its acks never blocks the calling thread — the stall fills
    its TCP window, then the writer's per-frame bound or the bounded
    outbound queue flips the session closed.  Either way the shed costs
    THIS session, and every send call stays O(1)."""
    import socket as socket_mod

    from go_crdt_playground_tpu.serve.session import Session

    a, b = socket_mod.socketpair()
    try:
        # tiny buffers so the window fills after a few frames
        a.setsockopt(socket_mod.SOL_SOCKET, socket_mod.SO_SNDBUF, 4096)
        b.setsockopt(socket_mod.SOL_SOCKET, socket_mod.SO_RCVBUF, 4096)
        s = Session(a, send_timeout_s=0.2, queue_depth=64)
        body = b"x" * 8192
        t0 = time.monotonic()
        sends = 0
        max_send_s = 0.0
        while True:
            s0 = time.monotonic()
            ok = s.send(protocol.MSG_ACK, body)
            max_send_s = max(max_send_s, time.monotonic() - s0)
            if not ok:
                break
            sends += 1
            assert sends < 10_000, "send never shed the stalled reader"
        elapsed = time.monotonic() - t0
        assert s.closed
        # the caller was never the one paying the stall: no single
        # enqueue blocked anywhere near the writer's socket bound
        assert max_send_s < 0.1, f"send() blocked {max_send_s:.3f}s"
        assert elapsed < 5.0, f"shed took {elapsed:.1f}s despite bounds"
        assert not s.send(protocol.MSG_ACK, b"y")  # closed: instant no-op
    finally:
        for sock in (a, b):
            try:
                sock.close()
            except OSError:
                pass


def test_session_writer_decouples_sessions():
    """The point of the per-session queues: one read-stalled client
    must not delay another session's replies THROUGH THE SAME CALLING
    THREAD (pre-refactor, the batcher serialized one SEND_TIMEOUT_S
    stall per stalled client per batch)."""
    import socket as socket_mod

    from go_crdt_playground_tpu.net import framing
    from go_crdt_playground_tpu.serve.session import Session

    a1, b1 = socket_mod.socketpair()  # stalled: b1 never read
    a2, b2 = socket_mod.socketpair()  # healthy: b2 read below
    try:
        a1.setsockopt(socket_mod.SOL_SOCKET, socket_mod.SO_SNDBUF, 4096)
        b1.setsockopt(socket_mod.SOL_SOCKET, socket_mod.SO_RCVBUF, 4096)
        stalled = Session(a1, send_timeout_s=0.2, queue_depth=16)
        healthy = Session(a2, send_timeout_s=0.2)
        body = b"x" * 8192
        # interleave like a batcher acking a mixed batch: the stalled
        # session absorbs/sheds, the healthy one must deliver promptly
        t0 = time.monotonic()
        for i in range(20):
            stalled.send(protocol.MSG_ACK, body)
            assert healthy.send(protocol.MSG_ACK,
                                protocol.encode_ack(i))
        enqueue_s = time.monotonic() - t0
        assert enqueue_s < 1.0, f"interleaved sends took {enqueue_s:.1f}s"
        b2.settimeout(10.0)
        for i in range(20):  # every healthy ack arrives, in order
            msg_type, reply = framing.recv_frame(b2, timeout=10.0)
            assert msg_type == protocol.MSG_ACK
            assert protocol.decode_ack(reply) == i
        stalled.close()
        healthy.close()
    finally:
        for sock in (a1, b1, a2, b2):
            try:
                sock.close()
            except OSError:
                pass


def test_poison_batch_rejects_retryable_and_keeps_serving(tmp_path):
    """An apply failure rejects the batch's ops RETRYABLE-typed — a
    disk failure (OSError: the WAL append/fsync path) classifies as
    ``StorageDegraded``, any other apply fault as ``Overloaded``,
    never the permanent InvalidOp — and the batcher keeps serving.
    While the storage-degrade window is armed, writes shed typed at
    ADMISSION but reads still serve; the window clears once a probe
    batch survives."""
    import time as time_mod

    fe = ServeFrontend(E, A, durable_dir=str(tmp_path / "n0"),
                       max_batch=4, flush_ms=0.5)
    inner = fe.node.ingest_batch
    poison = {"kind": OSError}

    def flaky(*args, **kwargs):
        if poison["kind"] is not None:
            raise poison["kind"]("injected disk error")
        return inner(*args, **kwargs)

    fe.node.ingest_batch = flaky
    fe.serve()
    try:
        with ServeClient(_addr(fe)) as c:
            with pytest.raises(protocol.StorageDegraded, match="retry"):
                c.add(1)
            # the degrade window is armed: writes shed typed at
            # ADMISSION (never reach the queue), reads keep serving
            assert fe.batcher.storage_degraded()
            with pytest.raises(protocol.StorageDegraded):
                c.add(1)
            members, _ = c.members()
            assert members == []
            # a non-disk apply fault stays the generic retryable class
            poison["kind"] = RuntimeError
            deadline = time_mod.monotonic() + 10.0
            saw_overloaded = False
            while time_mod.monotonic() < deadline:
                try:
                    c.add(1)
                except protocol.StorageDegraded:
                    time_mod.sleep(0.05)  # window still armed
                except protocol.Overloaded:
                    saw_overloaded = True
                    break
            assert saw_overloaded
            poison["kind"] = None  # heal the fault
            deadline = time_mod.monotonic() + 10.0
            while True:  # the next admitted batch is the disk probe
                try:
                    c.add(2)
                    break
                except protocol.ServeError:
                    assert time_mod.monotonic() < deadline
                    time_mod.sleep(0.05)
            assert not fe.batcher.storage_degraded()
            members, _ = c.members()
        assert members == [2]
        snap = fe.recorder.snapshot()
        assert snap["counters"]["serve.batch_errors"] >= 1
        assert snap["counters"]["serve.shed.storage"] >= 1
    finally:
        fe.close()


def test_client_on_result_fires_on_connection_death():
    """Review fix: ops resolved by the server going away must reach the
    on_result tally (outcome unknown), not read as forever-unresolved."""
    import socket as socket_mod

    listener = socket_mod.create_server(("127.0.0.1", 0))
    results = []
    try:
        c = ServeClient(listener.getsockname()[:2],
                        on_result=results.append)
        conn, _ = listener.accept()
        op = c.submit_async(protocol.OP_ADD, [1])
        conn.close()  # server dies without answering
        with pytest.raises(ConnectionError):
            op.wait(10.0)
        assert len(results) == 1 and results[0] is op
        assert isinstance(op.error, ConnectionError)
        c.close()
    finally:
        listener.close()


def test_connection_cap_sheds_excess_dials(tmp_path):
    """Review fix: the client listener bounds its reader threads (the
    net/peer.py _conn_slots pattern) — at capacity a new dial is shed
    (connection dropped), and a released slot admits again."""
    fe = ServeFrontend(E, A, durable_dir=str(tmp_path / "n0"),
                       max_conns=2, flush_ms=0.5)
    fe.serve()
    try:
        c1 = ServeClient(_addr(fe))
        c2 = ServeClient(_addr(fe))
        c1.add(1)
        c2.add(2)
        # third dial: TCP-accepted then immediately dropped by the slot
        # gate — the first use fails with a connection error
        c3 = ServeClient(_addr(fe))
        with pytest.raises((ConnectionError, OSError)):
            c3.add(3)
        c3.close()
        c1.close()
        deadline = time.monotonic() + 10.0
        c4 = None
        while time.monotonic() < deadline:  # c1's slot frees asynchronously
            try:
                c4 = ServeClient(_addr(fe))
                c4.add(4)
                break
            except (ConnectionError, OSError):
                if c4 is not None:
                    c4.close()
                    c4 = None
                time.sleep(0.05)
        assert c4 is not None, "released slot never admitted a new dial"
        c4.close()
        c2.close()
        assert fe.recorder.snapshot()["counters"][
            "serve.shed.connections"] >= 1
    finally:
        fe.close()


def test_oversized_frame_drops_connection(tmp_path):
    """Review fix: a hostile length header (within framing's 1 GiB peer
    limit but far above any legal serve frame) is refused before any
    body byte is buffered — the connection drops, the frontend lives."""
    import socket as socket_mod

    from go_crdt_playground_tpu.net import framing
    from go_crdt_playground_tpu.utils import wire

    fe = ServeFrontend(E, A, durable_dir=str(tmp_path / "n0"))
    fe.serve()
    try:
        raw = socket_mod.create_connection(_addr(fe), timeout=10.0)
        head = bytearray(framing.MAGIC)
        head.append(protocol.MSG_OP)
        wire._put_varint(head, 64 << 20)  # declares a 64 MiB body
        raw.sendall(bytes(head))
        assert raw.recv(1) == b""  # server dropped us without buffering
        raw.close()
        # the frontend still serves
        with ServeClient(_addr(fe)) as c:
            c.add(1)
            assert c.members()[0] == [1]
    finally:
        fe.close()


def test_close_is_idempotent_and_queryable_metrics(tmp_path):
    fe = ServeFrontend(E, A, durable_dir=str(tmp_path / "n0"))
    fe.serve()
    fe.close()
    fe.close()  # second close is a no-op, not an error
    assert os.path.isdir(str(tmp_path / "n0"))


# ---------------------------------------------------------------------------
# live-resharding wire verbs (serve/protocol.py + frontend slice handlers)
# ---------------------------------------------------------------------------


def test_reshard_and_slice_protocol_roundtrips():
    body = protocol.encode_reshard(7, protocol.RESHARD_JOIN, "s9",
                                   ("10.0.0.1", 4242))
    assert protocol.decode_reshard(body) == (
        7, protocol.RESHARD_JOIN, "s9", ("10.0.0.1", 4242))
    body = protocol.encode_reshard(8, protocol.RESHARD_LEAVE, "s1")
    assert protocol.decode_reshard(body) == (
        8, protocol.RESHARD_LEAVE, "s1", None)
    with pytest.raises(ValueError):
        protocol.encode_reshard(1, protocol.RESHARD_JOIN, "x")  # no addr
    with pytest.raises(ValueError):
        protocol.encode_reshard(1, protocol.RESHARD_LEAVE, "x",
                                ("h", 1))  # addr forbidden
    with pytest.raises(ValueError):
        protocol.encode_reshard(1, 9, "x")  # unknown mode
    with pytest.raises(ProtocolError):
        protocol.decode_reshard(body + b"\x00")  # trailing bytes

    body = protocol.encode_reshard_reply(3, True, {"moved": 5})
    assert protocol.decode_reshard_reply(body) == (3, True, {"moved": 5})
    body = protocol.encode_reshard_reply(4, False, {"reason": "nope"})
    assert protocol.decode_reshard_reply(body) == (
        4, False, {"reason": "nope"})

    body = protocol.encode_slice_pull(11, [4, 9, 60])
    assert protocol.decode_slice_pull(body) == (11, [4, 9, 60])
    with pytest.raises(ValueError):
        protocol.encode_slice_pull(1, [])
    payload = b"\x01opaque-payload-bytes"
    body = protocol.encode_slice_state(12, payload)
    assert protocol.decode_slice_state(body) == (12, payload)
    body = protocol.encode_slice_push(13, payload)
    assert protocol.decode_slice_push(body) == (13, payload)
    with pytest.raises(ProtocolError):
        protocol.decode_slice_push(b"")


def test_slice_pull_push_transfers_state(frontend, tmp_path):
    """The handoff transfer verbs end to end: pull a slice off one
    frontend, push it into another — the recipient serves the moved
    elements (incl. a deletion's absence), its other keys untouched,
    and the push is durable (WAL-logged) by ack time."""
    recipient = ServeFrontend(E, A, actor=1,
                              durable_dir=str(tmp_path / "recipient"),
                              max_batch=8, flush_ms=1.0)
    recipient.serve()
    try:
        with ServeClient(_addr(frontend)) as c:
            c.add(1, 2, 3, 9)
            c.delete(2)
            with pytest.raises(protocol.InvalidOp):
                c.slice_pull([E + 1])
            payload = c.slice_pull([1, 2, 3])
        with ServeClient(_addr(recipient)) as c:
            c.add(50)
            c.slice_push(payload)
            members, _ = c.members()
        # moved slice present (2 stays deleted), other keys untouched,
        # un-pulled donor keys (9) did not leak over
        assert members == [1, 3, 50]
        snap = frontend.recorder.snapshot()
        assert snap["counters"]["serve.slice.pulls"] == 1
        rsnap = recipient.recorder.snapshot()
        assert rsnap["counters"]["serve.slice.pushes"] == 1
    finally:
        recipient.close()


def test_slice_transfer_survives_vv_inflation():
    """Review-found acked-op-loss regression: slice pushes join the
    donor's FULL vv into the recipient, so after one handoff the
    recipient's vv covers donor dots it never received.  A LATER slice
    moving one of those dots here must still land — MODE_SLICE applies
    by overwrite (ops/delta.slice_apply), not vv arbitration, which
    would read the lane as already-seen and silently drop it."""
    import numpy as np

    from go_crdt_playground_tpu.net.peer import Node

    donor = Node(1, 32, 4)
    donor.add(5, 9)  # two dots in lane 1
    recip = Node(2, 32, 4)
    m = np.zeros(32, bool)
    m[5] = True
    recip.apply_payload_body(donor.extract_slice(m))  # move 5 only
    assert list(recip.members()) == [5]
    m = np.zeros(32, bool)
    m[9] = True
    later = donor.extract_slice(m)
    recip.apply_payload_body(later)  # 9's dot is already vv-covered
    assert list(recip.members()) == [5, 9], \
        "later slice dropped by inflated-vv arbitration"
    # retry idempotence (the push retry path): same payload, same state
    recip.apply_payload_body(later)
    assert list(recip.members()) == [5, 9]
    # authoritative overwrite: a deletion in the slice erases the
    # recipient's stale present copy even though the deletion dot is
    # long vv-covered (the leave-returns-a-deleted-element path)
    donor.delete(5)
    m = np.zeros(32, bool)
    m[5] = True
    recip.apply_payload_body(donor.extract_slice(m))
    assert list(recip.members()) == [9]
