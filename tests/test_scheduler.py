"""Conflict-aware admission scheduler (serve/scheduler.py, DESIGN.md
§25): key-runs, single-chunk emission with hot-tail carryover, and the
ordering contract.

The pinned surface is the §25 triple: (1) per-key FIFO survives every
reordering AND every deferral — ops sharing a key never swap, across
batches included; (2) the emitted order IS the durable order — a 2-D
mesh target fed the scheduler's emission with its stripe hint lands
BITWISE identical to a plain sequential node fed the same emitted log;
(3) the starvation bound — a cold op ships in the super-batch it was
drained into, a hot run's deferred tail re-enters at the FRONT of the
next one.  Hints are advisory: an adversarial stripe assignment may
cost cuts, never correctness.
"""

import numpy as np
import pytest

import jax

from go_crdt_playground_tpu.net.peer import Node
from go_crdt_playground_tpu.obs import Recorder
from go_crdt_playground_tpu.parallel.meshtarget2d import (
    Mesh2DApplyTarget, plan_stripes)
from go_crdt_playground_tpu.serve import protocol
from go_crdt_playground_tpu.serve.admission import AdmissionQueue, OpRequest
from go_crdt_playground_tpu.serve.batcher import MicroBatcher
from go_crdt_playground_tpu.serve.scheduler import (ConflictScheduler,
                                                    key_runs, plan_emit)


class _Op:
    """The minimal ``.elements``-bearing shape schedule() contracts on."""

    __slots__ = ("req_id", "elements")

    def __init__(self, req_id, elements):
        self.req_id = req_id
        self.elements = list(elements)


class _Session:
    """Ack sink for batcher-level tests: records every reply in order."""

    def __init__(self):
        self.sent = []

    def send(self, kind, body):
        self.sent.append((kind, bytes(body)))
        return True


def _assert_states_equal(a, b, context=""):
    for name in a._fields:
        xa, xb = np.asarray(getattr(a, name)), np.asarray(getattr(b, name))
        assert np.array_equal(xa, xb), (context, name)


# ---------------------------------------------------------------------------
# key_runs
# ---------------------------------------------------------------------------


def test_key_runs_partitions_transitively():
    # {0,1} bridges key a=5 and b=9 through op 2's {5, 9}; op 3 is its
    # own cold run; op 4 rejoins the bridged run through key 9
    runs = key_runs([[5], [9], [5, 9], [77], [9]])
    assert runs == [[0, 1, 2, 4], [3]]


def test_key_runs_keeps_arrival_order_within_run():
    runs = key_runs([[1], [2], [1], [1], [2]])
    assert runs == [[0, 2, 3], [1, 4]]


def test_key_runs_empty_selector_is_singleton():
    assert key_runs([[], [3], []]) == [[0], [1], [2]]


# ---------------------------------------------------------------------------
# plan_emit: single chunk + carryover
# ---------------------------------------------------------------------------


def test_plan_emit_rejects_bad_shape():
    with pytest.raises(ValueError):
        plan_emit([[1]], 0, 4)
    with pytest.raises(ValueError):
        plan_emit([[1]], 2, 0)


def test_plan_emit_hot_tail_defers_cold_head_ships():
    # dp=2, cap=2: a 5-op hot run on key 0 plus one cold op on key 9.
    # The hot run takes one whole stripe (2 rows), the cold op the
    # other; the hot TAIL (3 rows) defers — the cold op must NOT.
    keys = [[0], [0], [0], [9], [0], [0]]
    order, assign, deferred = plan_emit(keys, 2, 2)
    assert len(order) == len(assign) == 3
    assert sorted(order + deferred) == list(range(6))
    assert 3 in order  # the cold op shipped this super-batch
    assert deferred == sorted(deferred)  # carryover re-enters FIFO
    # hot rows emitted are the run's HEAD, in arrival order
    hot_emitted = [i for i in order if i != 3]
    assert hot_emitted == [0, 1]
    assert deferred == [2, 4, 5]
    # one stripe per run: the hot rows share one hint, the cold op the
    # other
    hints = {keys[i][0]: assign[j] for j, i in enumerate(order)}
    assert hints[0] != hints[9]


def test_plan_emit_single_chunk_never_overflows():
    rng = np.random.default_rng(5)
    for trial in range(50):
        dp = int(rng.integers(1, 5))
        cap = int(rng.integers(1, 6))
        n = int(rng.integers(1, dp * cap + 1))
        key_lists = [[int(k) for k in rng.integers(0, 6, rng.integers(1, 3))]
                     for _ in range(n)]
        order, assign, deferred = plan_emit(key_lists, dp, cap)
        assert sorted(order + deferred) == list(range(n)), trial
        assert len(assign) == len(order)
        loads = np.bincount(assign, minlength=dp) if assign else \
            np.zeros(dp, int)
        assert loads.max(initial=0) <= cap, trial
        # per-key FIFO across emission + deferral: ops sharing a run
        # appear in arrival order in (emitted ++ deferred)
        seq = order + deferred
        pos = {i: j for j, i in enumerate(seq)}
        for run in key_runs(key_lists):
            assert [pos[i] for i in run] == sorted(pos[i] for i in run), trial
        # a run lands on ONE stripe (the coalescing guarantee)
        stripe_of = {i: assign[j] for j, i in enumerate(order)}
        for run in key_runs(key_lists):
            stripes = {stripe_of[i] for i in run if i in stripe_of}
            assert len(stripes) <= 1, trial


def test_plan_emit_cold_ops_never_defer():
    # while any run remains unplaced, placed < dp*cap, so every run's
    # head gets a slot: with all-singleton input NOTHING defers
    rng = np.random.default_rng(6)
    for _ in range(20):
        dp = int(rng.integers(1, 5))
        cap = int(rng.integers(1, 6))
        n = int(rng.integers(1, dp * cap + 1))
        key_lists = [[int(i)] for i in rng.choice(10_000, n, replace=False)]
        order, _, deferred = plan_emit(key_lists, dp, cap)
        assert deferred == []
        assert sorted(order) == list(range(n))


# ---------------------------------------------------------------------------
# ConflictScheduler: streaming FIFO + observability
# ---------------------------------------------------------------------------


def test_scheduler_stream_fifo_with_key_audit():
    """The batcher-shaped stream: each round drains fresh ops, prepends
    the last round's deferral, schedules.  Across the WHOLE stream each
    key's ops must emit in arrival order, and every op ships once."""
    rng = np.random.default_rng(8)
    dp, width = 4, 16
    sched = ConflictScheduler(dp)
    keys_of = {}
    emitted_ids, carry, next_id = [], [], 0
    for _ in range(30):
        fresh = []
        for _ in range(width - len(carry)):
            ks = [int(k) for k in rng.choice(8, rng.integers(1, 3),
                                             replace=False)]
            keys_of[next_id] = ks
            fresh.append(_Op(next_id, ks))
            next_id += 1
        emitted, _, carry = sched.schedule(carry + fresh, width)
        emitted_ids.extend(r.req_id for r in emitted)
    while carry:
        emitted, _, carry = sched.schedule(carry, width)
        emitted_ids.extend(r.req_id for r in emitted)
    assert sorted(emitted_ids) == list(range(next_id))
    pos = {i: j for j, i in enumerate(emitted_ids)}
    per_key = {}
    for i in range(next_id):
        for k in keys_of[i]:
            per_key.setdefault(k, []).append(pos[i])
    for k, positions in per_key.items():
        assert positions == sorted(positions), f"key {k} reordered"


def test_scheduler_metrics_flow():
    rec = Recorder()
    sched = ConflictScheduler(2, recorder=rec)
    # 5 ops on key 0 (hot: cap=2 → 3 defer) + 1 cold: 2 runs, 4
    # coalesced rows, stripe_fill = 3/4
    batch = [_Op(i, [0]) for i in range(5)] + [_Op(5, [9])]
    emitted, hint, deferred = sched.schedule(batch, 4)
    assert rec.counter("sched.keyruns") == 2
    assert rec.counter("sched.coalesced_rows") == 4
    assert rec.counter("sched.deferred_rows") == 3
    assert rec.gauge("sched.stripe_fill") == pytest.approx(3 / 4)
    snap = rec.snapshot()
    assert snap["observations"]["sched.reorder_distance"]["n"] == 3
    assert [r.req_id for r in deferred] == [2, 3, 4]


def test_scheduler_rejects_bad_dp():
    with pytest.raises(ValueError):
        ConflictScheduler(0)


# ---------------------------------------------------------------------------
# batcher carryover: deferral acks next batch, at the front
# ---------------------------------------------------------------------------


class _RecordingTarget:
    """ApplyTarget stub recording packed batches (no jax)."""

    def __init__(self, num_elements, ingest_stripes):
        self.num_elements = num_elements
        self.ingest_stripes = ingest_stripes
        self.calls = []

    def ingest_batch(self, add_rows, del_rows, live, stripe_hint=None):
        self.calls.append((add_rows.copy(), live.copy(),
                           None if stripe_hint is None
                           else stripe_hint.copy()))


def test_batcher_carry_acks_deferred_next_batch_first():
    dp, mb, E = 2, 2, 32
    target = _RecordingTarget(E, dp)
    q = AdmissionQueue(64)
    sched = ConflictScheduler(dp)
    b = MicroBatcher(target, q, max_batch=mb, scheduler=sched)
    sess = _Session()
    # width=4, cap=2: four hot ops on key 3 → 2 emit, 2 carry
    hot = [OpRequest(i, protocol.OP_ADD, [3], None, sess, 0.0)
           for i in range(4)]
    b._apply(list(hot))
    assert len(target.calls) == 1
    acked = [protocol.decode_ack(body) for _, body in sess.sent]
    assert acked == [0, 1]  # the hot head, in arrival order
    assert [r.req_id for r in b._carry] == [2, 3]
    # next round: a fresh hot op arrives AFTER the carried tail — the
    # tail must precede it (per-key FIFO across the deferral) and the
    # cold op still ships alongside
    late = [OpRequest(4, protocol.OP_ADD, [3], None, sess, 0.0),
            OpRequest(5, protocol.OP_ADD, [7], None, sess, 0.0)]
    b._apply(late)
    acked = [protocol.decode_ack(body) for _, body in sess.sent]
    assert acked[:2] == [0, 1]
    # the carried tail [2, 3] rejoined its run AHEAD of the newer hot
    # op 4, which (run of 3, cap 2) defers in turn; the cold op never
    # starves
    assert 2 in acked and 3 in acked and 5 in acked and 4 not in acked
    assert [r.req_id for r in b._carry] == [4]
    # drain flushes the last tail even with an empty queue
    q.close()
    b._flush_remaining()
    acked = [protocol.decode_ack(body) for _, body in sess.sent]
    assert sorted(acked) == list(range(6))
    assert b._carry == []


def test_batcher_hint_rides_to_target():
    dp, mb, E = 2, 2, 32
    target = _RecordingTarget(E, dp)
    sched = ConflictScheduler(dp)
    b = MicroBatcher(target, AdmissionQueue(64), max_batch=mb,
                     scheduler=sched)
    sess = _Session()
    b._apply([OpRequest(i, protocol.OP_ADD, [k], None, sess, 0.0)
              for i, k in enumerate([1, 2, 1])])
    (add, live, hint), = target.calls
    assert add.shape == (4, E) and hint.shape == (4,)
    assert live.sum() == 3 and (hint[live] >= 0).all()
    assert (hint[~live] == -1).all()
    # the key-1 run coalesced onto ONE stripe
    rows_k1 = np.where(add[:, 1])[0]
    assert len(set(hint[rows_k1].tolist())) == 1


# ---------------------------------------------------------------------------
# the §25 durable-order contract: emitted order ⇒ bitwise mesh parity
# ---------------------------------------------------------------------------


E2, A2 = 256, 4


def _zipf_batches(rng, rounds, width, s=1.2):
    p = np.arange(1, E2 + 1, dtype=np.float64) ** -s
    p /= p.sum()
    for _ in range(rounds):
        n = int(rng.integers(1, width + 1))
        yield [[int(k)] for k in rng.choice(E2, size=n, p=p)]


@pytest.mark.parametrize("shape", ["2x2", "4x2"])
def test_mesh2d_scheduled_stream_bitwise_parity(shape):
    """The tentpole pin: a dp×mp mesh fed the scheduler's emission +
    hint, batch after batch WITH carryover, lands bitwise identical to
    a plain sequential node fed the same emitted log — and the hinted
    emission plans with ZERO cuts (the scheduler's whole point)."""
    dp, mp = (int(x) for x in shape.split("x"))
    if jax.device_count() < dp * mp:
        pytest.skip(f"needs {dp * mp} devices")
    rng = np.random.default_rng(31)
    mb = 2
    width = dp * mb
    cap = mb
    sched = ConflictScheduler(dp)
    plain = Node(0, E2, A2)
    mesh = Mesh2DApplyTarget(0, E2, A2, mesh_shape=shape)
    next_id, carry = 0, []
    total_cuts = 0
    for key_lists in _zipf_batches(rng, 8, width):
        fresh = [_Op(next_id + i, ks) for i, ks in enumerate(key_lists)]
        fresh = fresh[:max(0, width - len(carry))]
        next_id += len(fresh)
        emitted, assign, carry = sched.schedule(carry + fresh, width)
        if not emitted:
            continue
        add = np.zeros((width, E2), bool)
        live = np.zeros(width, bool)
        hint = np.full(width, -1, np.int32)
        for j, r in enumerate(emitted):
            add[j, r.elements] = True
            live[j] = True
            hint[j] = assign[j]
        dl = np.zeros((width, E2), bool)
        _, cuts = plan_stripes(add, dl, live, dp, cap, assign=hint)
        total_cuts += cuts
        plain.ingest_batch(add, dl, live)
        mesh.ingest_batch(add, dl, live, stripe_hint=hint)
    assert total_cuts == 0  # pre-striped emission: plan_stripes stops cutting
    _assert_states_equal(plain.state_slice(), mesh.state_slice(),
                         f"shape={shape}")


def test_mesh2d_adversarial_hint_is_safe():
    """A hostile/stale hint (every row pinned to stripe 0, or random
    junk) may cost cuts but must not change the state: ownership and
    capacity are enforced by plan_stripes itself."""
    if jax.device_count() < 4:
        pytest.skip("needs 4 devices")
    rng = np.random.default_rng(32)
    plain = Node(0, E2, A2)
    mesh = Mesh2DApplyTarget(0, E2, A2, mesh_shape="2x2")
    B = 8
    for trial in range(3):
        add = rng.random((B, E2)) < 0.02
        dl = rng.random((B, E2)) < 0.01
        live = rng.random(B) < 0.9
        hint = np.asarray([0] * B if trial == 0
                          else rng.integers(0, 2, B), np.int32)
        plain.ingest_batch(add, dl, live)
        mesh.ingest_batch(add, dl, live, stripe_hint=hint)
    _assert_states_equal(plain.state_slice(), mesh.state_slice(),
                         "adversarial hint")
