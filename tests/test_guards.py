"""uint32 overflow guards (SURVEY §5.2): clock-exhaustion detection."""

import pytest

import jax
import jax.numpy as jnp

from go_crdt_playground_tpu.models import awset
from go_crdt_playground_tpu.utils import guards


def test_headroom_fresh_state():
    state = awset.init(4, 8, 4)
    assert int(guards.counter_headroom(state.vv)) == guards.UINT32_MAX
    assert not bool(guards.overflow_risk(state.vv))
    assert guards.check_headroom(state) is state


def test_overflow_risk_trips_within_margin():
    state = awset.init(4, 8, 4)
    vv = state.vv.at[2, 1].set(guards.UINT32_MAX - 100)
    assert bool(guards.overflow_risk(vv))
    assert int(guards.counter_headroom(vv)) == 100
    with pytest.raises(OverflowError):
        guards.check_headroom(state._replace(vv=vv))


def test_overflow_risk_is_jit_safe():
    risky = jax.jit(guards.overflow_risk)
    vv = jnp.zeros((3, 3), jnp.uint32)
    assert not bool(risky(vv))
    assert bool(risky(vv.at[0, 0].set(guards.UINT32_MAX)))


def test_margin_boundary_exact():
    vv = jnp.zeros((2, 2), jnp.uint32).at[0, 0].set(
        guards.UINT32_MAX - guards.DEFAULT_MARGIN)
    # headroom == margin: not yet at risk
    assert not bool(guards.overflow_risk(vv))
    assert bool(guards.overflow_risk(vv + jnp.uint32(1)))
