"""utils/guards.py: uint32 overflow guards (SURVEY §5.2) + the shim
single-install registry the race detector builds on."""

import threading

import pytest

import jax
import jax.numpy as jnp

from go_crdt_playground_tpu.models import awset
from go_crdt_playground_tpu.utils import guards


def test_headroom_fresh_state():
    state = awset.init(4, 8, 4)
    assert int(guards.counter_headroom(state.vv)) == guards.UINT32_MAX
    assert not bool(guards.overflow_risk(state.vv))
    assert guards.check_headroom(state) is state


def test_overflow_risk_trips_within_margin():
    state = awset.init(4, 8, 4)
    vv = state.vv.at[2, 1].set(guards.UINT32_MAX - 100)
    assert bool(guards.overflow_risk(vv))
    assert int(guards.counter_headroom(vv)) == 100
    with pytest.raises(OverflowError):
        guards.check_headroom(state._replace(vv=vv))


def test_overflow_risk_is_jit_safe():
    risky = jax.jit(guards.overflow_risk)
    vv = jnp.zeros((3, 3), jnp.uint32)
    assert not bool(risky(vv))
    assert bool(risky(vv.at[0, 0].set(guards.UINT32_MAX)))


def test_margin_boundary_exact():
    vv = jnp.zeros((2, 2), jnp.uint32).at[0, 0].set(
        guards.UINT32_MAX - guards.DEFAULT_MARGIN)
    # headroom == margin: not yet at risk
    assert not bool(guards.overflow_risk(vv))
    assert bool(guards.overflow_risk(vv + jnp.uint32(1)))


# -- error paths / misuse --------------------------------------------------


def test_check_headroom_message_names_the_numbers():
    state = awset.init(1, 4, 2)
    vv = state.vv.at[0, 0].set(guards.UINT32_MAX - 3)
    with pytest.raises(OverflowError) as ei:
        guards.check_headroom(state._replace(vv=vv), margin=10)
    msg = str(ei.value)
    assert "3" in msg and "10" in msg, \
        "the operator needs headroom and margin, not just 'overflow'"


def test_check_headroom_zero_margin_never_raises():
    state = awset.init(1, 4, 2)
    vv = state.vv.at[0, 0].set(jnp.uint32(guards.UINT32_MAX))
    # margin 0: even a saturated clock passes (headroom 0 >= 0) — the
    # guard is strictly-less-than, so 0 disables it rather than making
    # every state fatal
    out = guards.check_headroom(state._replace(vv=vv), margin=0)
    assert int(out.vv[0, 0]) == guards.UINT32_MAX


def test_check_headroom_requires_vv_shaped_state():
    with pytest.raises(AttributeError):
        guards.check_headroom(object())


# -- shim install guard ----------------------------------------------------


def test_install_guard_claims_and_releases():
    g = guards.InstallGuard()
    g.install("k", owner="test")
    assert g.installed("k")
    g.uninstall("k")
    assert not g.installed("k")
    g.install("k")   # reinstall after release is legal
    g.uninstall("k")


def test_install_guard_double_install_raises_with_owner():
    g = guards.InstallGuard()
    g.install(("shim", 1), owner="first-owner")
    with pytest.raises(guards.AlreadyInstalledError) as ei:
        g.install(("shim", 1), owner="second")
    assert "first-owner" in str(ei.value)


def test_install_guard_unbalanced_uninstall_raises():
    g = guards.InstallGuard()
    with pytest.raises(KeyError):
        g.uninstall("never-installed")


def test_install_guard_is_thread_safe():
    g = guards.InstallGuard()
    wins, losses = [], []

    def claim():
        try:
            g.install("contended")
            wins.append(1)
        except guards.AlreadyInstalledError:
            losses.append(1)

    ts = [threading.Thread(target=claim) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert len(wins) == 1 and len(losses) == 7, (wins, losses)


def test_race_detector_shim_install_twice_raises_cleanly():
    """The satellite contract: installing the race-detector shim twice
    on one object must raise (AlreadyInstalledError), and the failed
    second install must leave the first installation working."""
    from go_crdt_playground_tpu.analysis.locksets import RaceDetector

    class Obj:
        def __init__(self):
            self._lock = threading.Lock()
            self.x = 0

    det = RaceDetector()
    obj = det.instrument(Obj())
    try:
        with pytest.raises(guards.AlreadyInstalledError):
            det.instrument(obj)
        obj.x = 1   # first shim still traces without blowing up
        assert det.stats()["objects_traced"] == 1
    finally:
        det.uninstall(obj)
