"""CI wrapper for the process-kill crash soak (tools/crash_soak.py).

Mirrors tests/test_chaos.py::test_chaos_soak_quick_mode: the --quick
sweep must complete, converge at every kill rate, actually kill and
corrupt (a green crash test with zero kills is a broken test), and
write a well-formed CRASH_CURVE.json.  slow-marked: it spawns real
node processes and SIGKILLs them, so tier-1 runtime never pays for it.
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))


@pytest.mark.slow
def test_crash_soak_quick_mode(tmp_path):
    import crash_soak

    out = str(tmp_path / "CRASH_CURVE.json")
    rc = crash_soak.main(["--quick", "--out", out])
    assert rc == 0, "crash soak failed (non-convergence, delta loss, or " \
                    "missing fallback exercise)"
    with open(out) as f:
        artifact = json.load(f)
    curve = artifact["curve"]
    assert any(e["kill_rate"] >= 0.2 for e in curve), \
        "quick sweep must include the >=0.2 SIGKILL acceptance rate"
    for e in curve:
        assert e["converged_runs"] == e["seeds"]
        assert e["delta_loss_violations"] == 0
    faulted = [e for e in curve if e["kill_rate"] > 0]
    assert all(e["kills"] > 0 for e in faulted), \
        "a crash soak that never killed anything proved nothing"
    assert any(sum(e["storage_faults"].get(k, 0)
                   for k in ("torn_writes", "bit_flips", "zero_fills")) > 0
               for e in faulted), "no storage faults were injected"
    assert any(e["corruption_injected"]
               and e["restore_counters"].get("restore.fallbacks", 0) > 0
               for e in faulted), \
        "the corrupt-newest-checkpoint fallback path was never exercised"
    # serve-path throughput ladder: the zero-delta-loss verdict above
    # covers BOTH WAL record modes — local δs wrote compact index-lane
    # records, applied peer payloads dense ones, and restores replayed
    # (compact-specific replay is pinned in tests/test_durability.py
    # and the serve soak's crash leg; a kill can land right after a
    # checkpoint truncation and leave one mode's tail empty)
    modes = artifact["wal_record_modes"]
    assert modes.get("wal.compact_records", 0) > 0, modes
    assert modes.get("wal.dense_records", 0) > 0, modes
    assert (modes.get("wal.replayed_compact", 0)
            + modes.get("wal.replayed_dense", 0)) > 0, modes
