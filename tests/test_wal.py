"""Delta WAL: framing, durability contract, tear handling, rotation.

The contract under test (utils/wal.py): a record is on disk before the
mutation it describes is acknowledged, recovery replays the intact
PREFIX of the log and discards everything at or after the first tear,
and opening a torn log repairs it in place so appends land clean.
"""

import os
import zlib

import pytest

from go_crdt_playground_tpu.obs import Recorder
from go_crdt_playground_tpu.utils.wal import (MAGIC, DeltaWal,
                                              encode_record, scan_records)


def _bodies(n, size=24):
    return [bytes([i % 256]) * size for i in range(n)]


# -- record framing ----------------------------------------------------------


def test_encode_scan_roundtrip():
    data = b"".join(encode_record(b) for b in _bodies(7))
    bodies, end, torn = scan_records(data)
    assert bodies == _bodies(7)
    assert end == len(data)
    assert not torn


def test_scan_empty_is_clean():
    assert scan_records(b"") == ([], 0, False)


def test_scan_stops_at_bad_magic():
    good = encode_record(b"alpha")
    bodies, end, torn = scan_records(good + b"\x00\x00garbage")
    assert bodies == [b"alpha"]
    assert end == len(good)
    assert torn


def test_scan_stops_at_truncated_record():
    data = b"".join(encode_record(b) for b in _bodies(3))
    for cut in range(1, 8):
        bodies, end, torn = scan_records(data[:-cut])
        assert bodies == _bodies(2), f"cut={cut}"
        assert torn


def test_scan_stops_at_crc_mismatch():
    recs = [encode_record(b) for b in _bodies(3)]
    # flip one bit inside the SECOND record's body
    bad = bytearray(recs[1])
    bad[len(MAGIC) + 2] ^= 0x10
    bodies, end, torn = scan_records(recs[0] + bytes(bad) + recs[2])
    assert bodies == _bodies(1)
    assert end == len(recs[0])
    assert torn  # and record 3, though intact, is after the tear: dropped


def test_record_crc_is_over_body():
    rec = encode_record(b"payload")
    assert rec[-4:] == zlib.crc32(b"payload").to_bytes(4, "little")
    assert rec.startswith(MAGIC)


# -- append / replay ---------------------------------------------------------


def test_append_replay_roundtrip(tmp_path):
    rec = Recorder()
    with DeltaWal(str(tmp_path / "wal"), recorder=rec) as w:
        for b in _bodies(5):
            w.append(b)
        assert list(w.records()) == _bodies(5)
        assert w.record_count() == 5
    counters = rec.snapshot()["counters"]
    assert counters["wal.appends"] == 5
    assert counters["wal.appended_bytes"] > 0


def test_replay_survives_reopen(tmp_path):
    p = str(tmp_path / "wal")
    with DeltaWal(p) as w:
        for b in _bodies(4):
            w.append(b)
    with DeltaWal(p) as w2:
        assert list(w2.records()) == _bodies(4)
        assert not w2.torn_tail_repaired


def test_append_after_close_raises(tmp_path):
    w = DeltaWal(str(tmp_path / "wal"))
    w.close()
    with pytest.raises(ValueError):
        w.append(b"late")


# -- tear repair -------------------------------------------------------------


def _newest_segment(dirpath):
    names = sorted(n for n in os.listdir(dirpath)
                   if n.startswith("wal-") and n.endswith(".log"))
    return os.path.join(dirpath, names[-1])


def test_open_repairs_torn_tail(tmp_path):
    p = str(tmp_path / "wal")
    with DeltaWal(p) as w:
        for b in _bodies(6):
            w.append(b)
    seg = _newest_segment(p)
    size = os.path.getsize(seg)
    with open(seg, "r+b") as f:
        f.truncate(size - 3)  # a torn write: mid-CRC cut
    rec = Recorder()
    with DeltaWal(p, recorder=rec) as w2:
        assert w2.torn_tail_repaired
        assert list(w2.records()) == _bodies(5)
        # the repaired tail is clean: appends after the tear replay fine
        w2.append(b"after-tear")
        assert list(w2.records()) == _bodies(5) + [b"after-tear"]
    assert rec.snapshot()["counters"]["wal.torn_tail"] == 1


def test_post_open_corruption_surfaces_in_records_scan(tmp_path):
    p = str(tmp_path / "wal")
    rec = Recorder()
    with DeltaWal(p, recorder=rec) as w:
        for b in _bodies(4):
            w.append(b)
        seg = _newest_segment(p)
        with open(seg, "r+b") as f:
            f.seek(os.path.getsize(seg) - 10)
            f.write(b"\x00\x00\x00")
        bodies = list(w.records())
    assert len(bodies) < 4  # prefix only
    assert rec.snapshot()["counters"]["wal.torn_tail"] == 1


# -- rotation / truncation ---------------------------------------------------


def test_segment_rotation_and_ordered_replay(tmp_path):
    p = str(tmp_path / "wal")
    with DeltaWal(p, segment_bytes=64) as w:
        for b in _bodies(10):
            w.append(b)
        segs = [n for n in os.listdir(p) if n.endswith(".log")]
        assert len(segs) > 1, "small segment_bytes must rotate"
        assert list(w.records()) == _bodies(10)


def test_tear_in_middle_segment_drops_later_segments(tmp_path):
    p = str(tmp_path / "wal")
    with DeltaWal(p, segment_bytes=64) as w:
        for b in _bodies(10):
            w.append(b)
    segs = sorted(n for n in os.listdir(p) if n.endswith(".log"))
    first = os.path.join(p, segs[0])
    with open(first, "r+b") as f:
        f.truncate(os.path.getsize(first) - 1)
    with DeltaWal(p) as w2:
        bodies = list(w2.records())
        # the prefix property across segments: everything after the tear
        # — including whole LATER segments — is discarded
        assert bodies == _bodies(len(bodies))
        assert len(bodies) < 10
        remaining = sorted(n for n in os.listdir(p) if n.endswith(".log"))
        assert len(remaining) <= 2  # repaired first + fresh open segment


def test_truncate_resets_and_never_reuses_seq(tmp_path):
    p = str(tmp_path / "wal")
    rec = Recorder()
    with DeltaWal(p, recorder=rec) as w:
        for b in _bodies(3):
            w.append(b)
        seq_before = max(int(n[4:-4]) for n in os.listdir(p)
                         if n.endswith(".log"))
        w.truncate()
        assert w.record_count() == 0
        seq_after = max(int(n[4:-4]) for n in os.listdir(p)
                        if n.endswith(".log"))
        assert seq_after > seq_before
        w.append(b"fresh")
        assert list(w.records()) == [b"fresh"]
    assert rec.snapshot()["counters"]["wal.truncations"] == 1


def test_validation():
    with pytest.raises(ValueError):
        DeltaWal("/tmp/never-created-wal-x", segment_bytes=8)


# -- stream_from: the replication tail reader (DESIGN.md §23) ----------------


def test_stream_from_basic_and_follow(tmp_path):
    """Contiguous (seq, body) pairs from the cursor; re-invoking with
    the advanced cursor follows new appends — the WAL_SYNC poll
    shape."""
    from go_crdt_playground_tpu.utils.wal import WalTruncated  # noqa: F401

    with DeltaWal(str(tmp_path / "wal")) as w:
        assert w.min_seq() == 1 and w.next_seq() == 1
        assert list(w.stream_from(1)) == []
        for b in _bodies(5):
            w.append(b)
        got = list(w.stream_from(1))
        assert got == list(enumerate(_bodies(5), start=1))
        assert list(w.stream_from(4)) == [(4, _bodies(5)[3]),
                                          (5, _bodies(5)[4])]
        # follow: the next batch starts where the last one ended
        cursor = got[-1][0] + 1
        assert cursor == w.next_seq() == 6
        w.append(b"later")
        assert list(w.stream_from(cursor)) == [(6, b"later")]
        with pytest.raises(ValueError):
            w.stream_from(0)


def test_stream_from_crosses_rotation_and_seal(tmp_path):
    """Record seqs stay contiguous across segment rotation AND an
    explicit seal (the checkpoint two-phase): no gap, no repeat."""
    with DeltaWal(str(tmp_path / "wal"), segment_bytes=64) as w:
        bodies = _bodies(12, size=40)  # ~46B framed: rotates every rec
        for b in bodies[:8]:
            w.append(b)
        sealed = w.seal()
        assert len(sealed) > 1  # rotation really happened
        for b in bodies[8:]:
            w.append(b)
        assert [s for s, _ in w.stream_from(1)] == list(range(1, 13))
        assert [b for _, b in w.stream_from(9)] == bodies[8:]


def test_stream_from_truncate_surfaces_typed(tmp_path):
    """A checkpoint truncation under the cursor is TYPED WalTruncated
    — never a silent gap — and carries the resume bounds."""
    from go_crdt_playground_tpu.utils.wal import WalTruncated

    with DeltaWal(str(tmp_path / "wal")) as w:
        for b in _bodies(4):
            w.append(b)
        w.truncate()
        assert w.min_seq() == w.next_seq() == 5
        with pytest.raises(WalTruncated) as ei:
            w.stream_from(3)
        assert ei.value.wanted == 3
        assert ei.value.min_seq == 5 and ei.value.next_seq == 5
        # the fresh cursor streams the post-truncate records
        w.append(b"after")
        assert list(w.stream_from(5)) == [(5, b"after")]


def test_stream_from_drop_segments_surfaces_typed(tmp_path):
    """The save_durable two-phase (seal + drop) retires sealed
    segments: a cursor below the new minimum is typed, a cursor at it
    streams the fresh-segment records."""
    from go_crdt_playground_tpu.utils.wal import WalTruncated

    with DeltaWal(str(tmp_path / "wal")) as w:
        for b in _bodies(6):
            w.append(b)
        sealed = w.seal()
        w.append(b"fresh-1")
        w.drop_segments(sealed)
        assert w.min_seq() == 7
        with pytest.raises(WalTruncated):
            w.stream_from(1)
        assert list(w.stream_from(7)) == [(7, b"fresh-1")]


def test_stream_from_torn_tail_stops_then_resumes(tmp_path):
    """A torn tail stops the stream AT the tear (committed prefix only,
    no exception — an in-flight append looks identical); after the
    next append heals the tail, the same cursor resumes cleanly."""
    p = str(tmp_path / "wal")
    with DeltaWal(p) as w:
        for b in _bodies(3):
            w.append(b)
        seg = max(int(n[4:-4]) for n in os.listdir(p)
                  if n.endswith(".log"))
        seg_path = os.path.join(p, f"wal-{seg:012d}.log")
        # a partial record past the committed end (a mid-append crash)
        with open(seg_path, "ab") as f:
            f.write(encode_record(b"torn!")[:-3])
        assert [s for s, _ in w.stream_from(1)] == [1, 2, 3]
        # heal: the dirty-append path truncates the partial back first
        w._dirty = True
        w.append(b"healed")
        assert list(w.stream_from(4)) == [(4, b"healed")]
        assert [s for s, _ in w.stream_from(1)] == [1, 2, 3, 4]


def test_stream_seq_numbering_rebuilds_at_open(tmp_path):
    """Record numbering is an INSTANCE property rebuilt from the scan:
    a reopened log re-counts from 1 (the WAL_SYNC nonce is how tailing
    standbys learn their cursors died with the old instance)."""
    p = str(tmp_path / "wal")
    with DeltaWal(p, segment_bytes=64) as w:
        for b in _bodies(5, size=40):
            w.append(b)
        assert w.next_seq() == 6
    with DeltaWal(p) as w2:
        assert w2.min_seq() == 1 and w2.next_seq() == 6
        assert [s for s, _ in w2.stream_from(1)] == [1, 2, 3, 4, 5]
        w2.append(b"post-reopen")
        assert list(w2.stream_from(6)) == [(6, b"post-reopen")]
