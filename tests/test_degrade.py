"""utils/degrade.DegradeWindow — the shared probe-window latch both
serving ladders ride (storage degradation in serve/batcher.py,
replication degradation in shard/replica.py).  Direct unit tests:
arm / probe-success clears / probe-failure re-arms / concurrent arm."""

import threading

import pytest

from go_crdt_playground_tpu.utils.degrade import DegradeWindow


class FakeClock:
    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


def test_validation():
    with pytest.raises(ValueError):
        DegradeWindow(0.0)
    with pytest.raises(ValueError):
        DegradeWindow(-1.0)


def test_arm_activates_then_expires():
    clk = FakeClock()
    w = DegradeWindow(1.0, clk)
    assert not w.active() and not w.armed_ever() and w.windows == 0
    assert w.arm() is True          # a fresh episode
    assert w.active() and w.armed_ever() and w.windows == 1
    clk.t += 0.5
    assert w.active()
    clk.t += 0.6                    # past the deadline: probe time
    assert not w.active()           # degraded behavior stops holding
    assert w.armed_ever()           # ...but the probe dispatcher still
    #                                 knows a probe is owed


def test_probe_success_clears():
    clk = FakeClock()
    w = DegradeWindow(1.0, clk)
    w.arm()
    clk.t += 2.0
    assert not w.active() and w.armed_ever()
    w.clear()                       # the probe succeeded
    assert not w.active() and not w.armed_ever()
    # a later failure is a NEW episode
    assert w.arm() is True
    assert w.windows == 2


def test_probe_failure_rearms_one_episode():
    clk = FakeClock()
    w = DegradeWindow(1.0, clk)
    assert w.arm() is True
    clk.t += 1.5                    # window lapsed; probe runs...
    assert w.arm() is False         # ...and fails: same episode extends
    assert w.windows == 1           # degraded EPISODES, not failures
    assert w.active()
    # arming while still active also extends without counting
    clk.t += 0.2
    assert w.arm() is False
    assert w.windows == 1


def test_concurrent_arm_counts_sanely():
    """Many threads arming at once (the batcher loop vs a re-raising
    teardown path): the latch must end ACTIVE with a sane episode
    count — at least one, never more than the racers."""
    w = DegradeWindow(5.0)
    n = 8
    barrier = threading.Barrier(n)

    def racer():
        barrier.wait()
        w.arm()

    threads = [threading.Thread(target=racer) for _ in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert w.active()
    assert 1 <= w.windows <= n
    w.clear()
    assert not w.active() and not w.armed_ever()
