"""The examples/ scripts are the switching user's first session — they
must stay runnable exactly as documented (python examples/<name>.py
from the repo root, no install)."""

import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


@pytest.mark.parametrize("name", ["quickstart.py", "tcp_sync.py"])
def test_example_runs_verbatim(name):
    proc = subprocess.run(
        [sys.executable, str(REPO / "examples" / name)],
        capture_output=True, text=True, timeout=240, cwd=str(REPO))
    assert proc.returncode == 0, proc.stderr
    assert "OK" in proc.stdout
