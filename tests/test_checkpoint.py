"""Checkpoint/resume: roundtrip fidelity and bitwise resume equivalence.

The contract (SURVEY §5.4): the packed tensors are the checkpoint, so a
gossip run interrupted by save+restore must land bitwise on the same
state as an uninterrupted run.
"""

import numpy as np
import pytest

import jax

from go_crdt_playground_tpu.models import awset, awset_delta
from go_crdt_playground_tpu.models.spec import AWSet, VersionVector
from go_crdt_playground_tpu.ops import lattices as L
from go_crdt_playground_tpu.parallel import gossip
from go_crdt_playground_tpu.utils import checkpoint as ckpt
from go_crdt_playground_tpu.utils.codec import ElementDict, pack_awsets


def _scenario_state():
    """Three spec replicas after a concurrent scenario, packed."""
    reps = [AWSet(actor=i, version_vector=VersionVector([0, 0, 0]))
            for i in range(3)]
    reps[0].add("Anne", "Bob")
    reps[1].add("Anne", "Carol")
    reps[2].add("Dave")
    reps[0].del_("Bob")
    d = ElementDict(capacity=16)
    arrays = pack_awsets(reps, d, num_actors=3)
    return awset.from_arrays(arrays), d


def assert_tree_equal(a, b):
    assert type(a) is type(b)
    for name in a._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, name)), np.asarray(getattr(b, name)),
            err_msg=name)


def test_roundtrip_awset(tmp_path):
    state, d = _scenario_state()
    p = str(tmp_path / "ck")
    ckpt.save_checkpoint(p, state, dictionary=d, step=7,
                         metadata={"note": "after scenario"})
    got = ckpt.restore_checkpoint(p)
    assert_tree_equal(state, got.state)
    assert got.step == 7
    assert got.metadata == {"note": "after scenario"}
    assert got.dictionary.state_dict() == d.state_dict()


def test_roundtrip_all_lattice_families(tmp_path):
    states = [
        L.gcounter_init(4, 4),
        L.pncounter_init(4, 4),
        L.twopset_init(4, 8),
        L.lwwmap_init(4, 8),
        L.mvregister_init(4, 4),
        awset_delta.init(3, 16, 3),
    ]
    for i, st in enumerate(states):
        p = str(tmp_path / f"ck{i}")
        ckpt.save_checkpoint(p, st)
        got = ckpt.restore_checkpoint(p)
        assert_tree_equal(st, got.state)


def test_resume_equivalence_bitwise(tmp_path):
    """gossip k rounds -> save -> restore -> gossip k more == gossip 2k."""
    state, _ = _scenario_state()
    R = state.vv.shape[0]
    perms = [gossip.ring_perm(R, o) for o in (1, 2, 1, 2)]

    uninterrupted = state
    for perm in perms:
        uninterrupted = gossip.gossip_round(uninterrupted, perm)

    half = state
    for perm in perms[:2]:
        half = gossip.gossip_round(half, perm)
    p = str(tmp_path / "mid")
    ckpt.save_checkpoint(p, half, step=2)
    resumed = ckpt.restore_checkpoint(p).state
    for perm in perms[2:]:
        resumed = gossip.gossip_round(resumed, perm)

    assert_tree_equal(uninterrupted, resumed)


def test_save_overwrites_previous_generation(tmp_path):
    state, d = _scenario_state()
    p = str(tmp_path / "ck")
    ckpt.save_checkpoint(p, state, step=1)
    bumped = state._replace(vv=state.vv + 1)
    ckpt.save_checkpoint(p, bumped, step=2)
    got = ckpt.restore_checkpoint(p)
    assert got.step == 2
    np.testing.assert_array_equal(np.asarray(got.state.vv),
                                  np.asarray(bumped.vv))
    # no stray temp files from either save
    assert [f.name for f in tmp_path.iterdir()] == ["ck"]


def _tamper_manifest(path, **updates):
    import json

    with np.load(path) as z:
        arrays = {k: z[k] for k in z.files}
    m = json.loads(arrays["__manifest__"].tobytes().decode())
    m.update(updates)
    arrays["__manifest__"] = np.frombuffer(
        json.dumps(m).encode(), np.uint8)
    with open(path, "wb") as f:
        np.savez(f, **arrays)


def test_unknown_state_type_degrades_to_arrays(tmp_path):
    state, _ = _scenario_state()
    p = str(tmp_path / "ck")
    ckpt.save_checkpoint(p, state)
    _tamper_manifest(p, state_type="FutureState")
    got = ckpt.restore_checkpoint(p)
    assert isinstance(got.state, dict)
    np.testing.assert_array_equal(np.asarray(got.state["vv"]),
                                  np.asarray(state.vv))


def test_newer_format_version_rejected(tmp_path):
    state, _ = _scenario_state()
    p = str(tmp_path / "ck")
    ckpt.save_checkpoint(p, state)
    _tamper_manifest(p, format_version=99)
    with pytest.raises(ValueError, match="newer"):
        ckpt.restore_checkpoint(p)


def test_sharded_checkpoint_roundtrip_on_mesh(tmp_path):
    """orbax-backed path: save a mesh-sharded state, restore onto the
    same mesh, bitwise equal with shardings preserved."""
    import jax

    from go_crdt_playground_tpu.parallel import mesh as mesh_mod
    from go_crdt_playground_tpu.utils import checkpoint_sharded as cs

    st = awset_delta.init(16, 32, 16)
    st = awset_delta.add_element(st, np.uint32(3), np.uint32(7))
    m = mesh_mod.make_mesh((4, 2))
    sharded = mesh_mod.shard_state(st, m)
    d = ElementDict(capacity=32, values=["a", "b"])
    path = cs.save_checkpoint_sharded(str(tmp_path / "ck"), sharded,
                                      dictionary=d, step=5,
                                      metadata={"round": 1})
    ck = cs.restore_checkpoint_sharded(path, target=sharded)
    assert ck.step == 5 and ck.metadata == {"round": 1}
    assert ck.dictionary.decode(1) == "b"
    assert type(ck.state).__name__ == "AWSetDeltaState"
    for name in st._fields:
        got = getattr(ck.state, name)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(getattr(st, name)), name)
        assert got.sharding == getattr(sharded, name).sharding, name


def test_sharded_checkpoint_restore_without_target(tmp_path):
    from go_crdt_playground_tpu.utils import checkpoint_sharded as cs

    st = awset.init(4, 8, 4)
    path = cs.save_checkpoint_sharded(str(tmp_path / "ck2"), st)
    ck = cs.restore_checkpoint_sharded(path)
    for name in st._fields:
        np.testing.assert_array_equal(np.asarray(getattr(ck.state, name)),
                                      np.asarray(getattr(st, name)), name)


def test_packed_and_ormap_states_round_trip_typed(tmp_path):
    """The bitpacked layouts and the OR-Map restore as their typed
    states (they previously degraded to plain dicts), bitwise intact —
    the packed form is the realistic at-scale checkpoint format (8x
    smaller membership arrays on disk)."""
    from go_crdt_playground_tpu.models import packed as packed_mod

    state = awset_delta.init(4, 96, 4)
    state = awset_delta.add_element(state, np.uint32(1), np.uint32(7))
    p = packed_mod.pack_awset_delta(state)
    path = str(tmp_path / "packed.ckpt")
    ckpt.save_checkpoint(path, p)
    ck = ckpt.restore_checkpoint(path)
    assert type(ck.state).__name__ == "PackedAWSetDeltaState"
    for name in p._fields:
        np.testing.assert_array_equal(np.asarray(getattr(ck.state, name)),
                                      np.asarray(getattr(p, name)),
                                      err_msg=name)

    om = L.ormap_init(4, 16, 4)
    om = L.ormap_put(om, np.uint32(0), np.uint32(3), np.uint32(9),
                     np.uint32(1))
    path2 = str(tmp_path / "ormap.ckpt")
    ckpt.save_checkpoint(path2, om)
    ck2 = ckpt.restore_checkpoint(path2)
    assert type(ck2.state).__name__ == "ORMapState"
    for name in om._fields:
        np.testing.assert_array_equal(np.asarray(getattr(ck2.state, name)),
                                      np.asarray(getattr(om, name)),
                                      err_msg=name)


def test_dotpacked_states_round_trip_typed(tmp_path):
    """The dot-word layouts restore as their typed states, bitwise
    intact — same contract as the other packed forms."""
    from go_crdt_playground_tpu.models import packed as packed_mod

    state = awset_delta.init(4, 96, 4)
    state = awset_delta.add_element(state, np.uint32(1), np.uint32(7))
    for pack, name in (
            (packed_mod.pack_awset_delta_dots, "DotPackedAWSetDeltaState"),
    ):
        p = pack(state)
        path = str(tmp_path / f"{name}.ckpt")
        ckpt.save_checkpoint(path, p)
        ck = ckpt.restore_checkpoint(path)
        assert type(ck.state).__name__ == name
        for f in p._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(ck.state, f)),
                np.asarray(getattr(p, f)), err_msg=f)

    from go_crdt_playground_tpu.models import awset as awset_mod

    aw = awset_mod.init(4, 96, 4)
    aw = awset_mod.add_element(aw, np.uint32(1), np.uint32(7))
    p = packed_mod.pack_awset_dots(aw)
    path = str(tmp_path / "dotset.ckpt")
    ckpt.save_checkpoint(path, p)
    ck = ckpt.restore_checkpoint(path)
    assert type(ck.state).__name__ == "DotPackedAWSetState"
    for f in p._fields:
        np.testing.assert_array_equal(np.asarray(getattr(ck.state, f)),
                                      np.asarray(getattr(p, f)),
                                      err_msg=f)
