"""Conformance + property tests for the additional CRDT families:
tensor kernels vs the spec_extra oracles, randomized, plus lattice laws
(commutativity / associativity / idempotence) and gossip integration.
"""

import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from go_crdt_playground_tpu.models import spec_extra as S
from go_crdt_playground_tpu.ops import lattices as L
from go_crdt_playground_tpu.parallel import gossip


# ---------------------------------------------------------------------------
# G-Counter / PN-Counter
# ---------------------------------------------------------------------------


def test_gcounter_conformance_randomized():
    rng = random.Random(0)
    R = 4
    spec = [S.GCounter(i, R) for i in range(R)]
    st = L.gcounter_init(R, R)
    for _ in range(200):
        if rng.random() < 0.7:
            r = rng.randrange(R)
            amt = rng.randint(1, 5)
            spec[r].inc(amt)
            st = L.gcounter_inc(st, np.uint32(r), np.uint32(amt))
        else:
            d, s = rng.randrange(R), rng.randrange(R)
            spec[d].merge(spec[s])
            merged = L.gcounter_join(
                jax.tree.map(lambda x: x[d], st),
                jax.tree.map(lambda x: x[s], st))
            st = jax.tree.map(lambda f, row: f.at[d].set(row), st, merged)
        assert np.array_equal(
            np.asarray(st.counts),
            np.array([c.counts for c in spec], np.uint32))
    for r in range(R):
        assert int(L.gcounter_value(st)[r]) == spec[r].value()


def test_pncounter_conformance_randomized():
    rng = random.Random(1)
    R = 4
    spec = [S.PNCounter(i, R) for i in range(R)]
    st = L.pncounter_init(R, R)
    for _ in range(200):
        if rng.random() < 0.7:
            r = rng.randrange(R)
            amt = rng.randint(-5, 5)
            if amt >= 0:
                spec[r].inc(amt)
            else:
                spec[r].dec(-amt)
            st = L.pncounter_add(st, np.uint32(r), np.int32(amt))
        else:
            d, s = rng.randrange(R), rng.randrange(R)
            spec[d].merge(spec[s])
            merged = L.pncounter_join(
                jax.tree.map(lambda x: x[d], st),
                jax.tree.map(lambda x: x[s], st))
            st = jax.tree.map(lambda f, row: f.at[d].set(row), st, merged)
    vals = np.asarray(L.pncounter_value(st))
    for r in range(R):
        assert int(vals[r]) == spec[r].value()


# ---------------------------------------------------------------------------
# 2P-Set
# ---------------------------------------------------------------------------


def test_twopset_conformance_randomized():
    rng = random.Random(2)
    R, E = 3, 12
    universe = [f"k{i}" for i in range(E)]
    spec = [S.TwoPSet() for _ in range(R)]
    st = L.twopset_init(R, E)
    for _ in range(200):
        p = rng.random()
        r = rng.randrange(R)
        e = rng.randrange(E)
        if p < 0.5:
            spec[r].add(universe[e])
            st = L.twopset_add(st, np.uint32(r), np.uint32(e))
        elif p < 0.75:
            spec[r].del_(universe[e])
            st = L.twopset_del(st, np.uint32(r), np.uint32(e))
        else:
            d, s = rng.randrange(R), rng.randrange(R)
            spec[d].merge(spec[s])
            merged = L.twopset_join(
                jax.tree.map(lambda x: x[d], st),
                jax.tree.map(lambda x: x[s], st))
            st = jax.tree.map(lambda f, row: f.at[d].set(row), st, merged)
        member = np.asarray(L.twopset_member(st))
        for r2 in range(R):
            got = sorted(universe[i] for i in np.nonzero(member[r2])[0])
            assert got == spec[r2].values(), r2


def test_twopset_remove_wins_forever():
    st = L.twopset_init(2, 4)
    st = L.twopset_add(st, np.uint32(0), np.uint32(1))
    st = L.twopset_del(st, np.uint32(0), np.uint32(1))
    st = L.twopset_add(st, np.uint32(0), np.uint32(1))  # re-add is futile
    assert not bool(L.twopset_member(st)[0, 1])
    # unobserved delete is a no-op
    st = L.twopset_del(st, np.uint32(1), np.uint32(2))
    assert not bool(st.removed[1, 2])


# ---------------------------------------------------------------------------
# LWW-Map
# ---------------------------------------------------------------------------


def test_lwwmap_conformance_randomized():
    rng = random.Random(3)
    R, E = 3, 8
    universe = [f"k{i}" for i in range(E)]
    spec = [S.LWWMap(actor=i) for i in range(R)]
    st = L.lwwmap_init(R, E)
    ts = 0
    for _ in range(200):
        p = rng.random()
        r = rng.randrange(R)
        e = rng.randrange(E)
        if p < 0.55:
            ts += 1
            v = rng.randrange(1000)
            spec[r].put(universe[e], v, ts)
            st = L.lwwmap_put(st, np.uint32(r), np.uint32(e), np.uint32(v),
                              np.uint32(ts), np.bool_(True))
        elif p < 0.7:
            ts += 1
            spec[r].delete(universe[e], ts)
            st = L.lwwmap_put(st, np.uint32(r), np.uint32(e), np.uint32(0),
                              np.uint32(ts), np.bool_(False))
        else:
            d, s = rng.randrange(R), rng.randrange(R)
            spec[d].merge(spec[s])
            merged = L.lwwmap_join(
                jax.tree.map(lambda x: x[d], st),
                jax.tree.map(lambda x: x[s], st))
            st = jax.tree.map(lambda f, row: f.at[d].set(row), st, merged)
        for r2 in range(R):
            live = np.asarray(st.live[r2])
            vals = np.asarray(st.val[r2])
            got = {universe[i]: int(vals[i]) for i in np.nonzero(live)[0]}
            assert got == spec[r2].items(), r2


def test_lwwmap_concurrent_same_ts_actor_tiebreak():
    st = L.lwwmap_init(2, 2)
    st = L.lwwmap_put(st, np.uint32(0), np.uint32(0), np.uint32(10),
                      np.uint32(5), np.bool_(True))
    st = L.lwwmap_put(st, np.uint32(1), np.uint32(0), np.uint32(20),
                      np.uint32(5), np.bool_(True))
    # merge both directions: higher actor (1) must win deterministically
    a = L.lwwmap_join(jax.tree.map(lambda x: x[0], st),
                      jax.tree.map(lambda x: x[1], st))
    b = L.lwwmap_join(jax.tree.map(lambda x: x[1], st),
                      jax.tree.map(lambda x: x[0], st))
    assert int(a.val[0]) == int(b.val[0]) == 20


# ---------------------------------------------------------------------------
# MV-Register
# ---------------------------------------------------------------------------


def test_mvregister_conformance_randomized():
    rng = random.Random(4)
    R = 4
    spec = [S.MVRegister(i, R) for i in range(R)]
    st = L.mvregister_init(R, R)
    for step in range(300):
        if rng.random() < 0.5:
            r = rng.randrange(R)
            v = rng.randrange(1, 1000)
            spec[r].write(v)
            st = L.mvregister_write(st, np.uint32(r), np.uint32(v))
        else:
            d, s = rng.randrange(R), rng.randrange(R)
            spec[d].merge(spec[s])
            merged = L.mvregister_join(
                jax.tree.map(lambda x: x[d], st),
                jax.tree.map(lambda x: x[s], st))
            st = jax.tree.map(lambda f, row: f.at[d].set(row), st, merged)
        for r2 in range(R):
            for name, arr in (("ctx", st.ctx), ("live", st.live),
                              ("cnt", st.cnt), ("val", st.val)):
                assert np.asarray(arr[r2]).tolist() == list(
                    getattr(spec[r2], name)), (step, r2, name)


def test_mvregister_concurrent_writes_both_visible():
    st = L.mvregister_init(2, 2)
    st = L.mvregister_write(st, np.uint32(0), np.uint32(7))
    st = L.mvregister_write(st, np.uint32(1), np.uint32(9))
    merged = L.mvregister_join(jax.tree.map(lambda x: x[0], st),
                               jax.tree.map(lambda x: x[1], st))
    vis = sorted(int(v) for v, l in zip(np.asarray(merged.val),
                                        np.asarray(merged.live)) if l)
    assert vis == [7, 9]
    # a subsequent write dominates both
    st2 = jax.tree.map(lambda f, row: f.at[0].set(row), st, merged)
    st2 = L.mvregister_write(st2, np.uint32(0), np.uint32(42))
    back = L.mvregister_join(jax.tree.map(lambda x: x[1], st2),
                             jax.tree.map(lambda x: x[0], st2))
    vis2 = [int(v) for v, l in zip(np.asarray(back.val),
                                   np.asarray(back.live)) if l]
    assert vis2 == [42]


# ---------------------------------------------------------------------------
# OR-Map
# ---------------------------------------------------------------------------


def test_ormap_conformance_randomized():
    rng = random.Random(6)
    R, E = 3, 8
    universe = [f"k{i}" for i in range(E)]
    spec = [S.ORMap(actor=i, num_actors=R) for i in range(R)]
    st = L.ormap_init(R, E, R)
    ts = 0
    for step in range(200):
        p = rng.random()
        r = rng.randrange(R)
        e = rng.randrange(E)
        if p < 0.5:
            ts += 1
            v = rng.randrange(1, 1000)
            spec[r].put(universe[e], v, ts)
            st = L.ormap_put(st, np.uint32(r), np.uint32(e), np.uint32(v),
                             np.uint32(ts))
        elif p < 0.7:
            spec[r].delete(universe[e])
            st = L.ormap_delete(st, np.uint32(r), np.uint32(e))
        else:
            d, s = rng.randrange(R), rng.randrange(R)
            spec[d].merge(spec[s])
            merged = L.ormap_join(
                jax.tree.map(lambda x: x[d], st),
                jax.tree.map(lambda x: x[s], st))
            st = jax.tree.map(lambda f, row: f.at[d].set(row), st, merged)
        for r2 in range(R):
            pres = np.asarray(st.present[r2])
            vals = np.asarray(st.val[r2])
            got = {universe[i]: int(vals[i]) for i in np.nonzero(pres)[0]}
            assert got == spec[r2].items(), (step, r2)


def test_ormap_concurrent_put_wins_over_delete():
    """The key membership inherits AWSet add-wins (awset_test.go:85-122's
    property lifted to maps)."""
    spec = [S.ORMap(actor=i, num_actors=2) for i in range(2)]
    st = L.ormap_init(2, 4, 2)
    spec[0].put("k", 1, 1)
    st = L.ormap_put(st, np.uint32(0), np.uint32(0), np.uint32(1), np.uint32(1))
    spec[1].merge(spec[0])
    m = L.ormap_join(jax.tree.map(lambda x: x[1], st),
                     jax.tree.map(lambda x: x[0], st))
    st = jax.tree.map(lambda f, row: f.at[1].set(row), st, m)
    # concurrent: replica 0 deletes, replica 1 re-puts
    spec[0].delete("k"); spec[1].put("k", 7, 2)
    st = L.ormap_delete(st, np.uint32(0), np.uint32(0))
    st = L.ormap_put(st, np.uint32(1), np.uint32(0), np.uint32(7), np.uint32(2))
    spec[0].merge(spec[1])
    m = L.ormap_join(jax.tree.map(lambda x: x[0], st),
                     jax.tree.map(lambda x: x[1], st))
    assert bool(m.present[0])       # writer wins
    assert int(m.val[0]) == 7
    assert spec[0].get("k") == 7


# ---------------------------------------------------------------------------
# Lattice laws + gossip integration
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("family", ["gcounter", "twopset", "lww", "mvreg"])
def test_lattice_laws(family):
    """Idempotence, commutativity(-on-read), associativity on random
    states."""
    rng = random.Random(5)

    def rand_state():
        if family == "gcounter":
            st = L.gcounter_init(3, 3)
            for _ in range(10):
                st = L.gcounter_inc(st, np.uint32(rng.randrange(3)),
                                    np.uint32(rng.randint(1, 9)))
            return st, L.gcounter_join, lambda s: np.asarray(s.counts)
        if family == "twopset":
            st = L.twopset_init(3, 8)
            for _ in range(15):
                f = L.twopset_add if rng.random() < 0.6 else L.twopset_del
                st = f(st, np.uint32(rng.randrange(3)),
                       np.uint32(rng.randrange(8)))
            return st, L.twopset_join, lambda s: np.asarray(
                L.twopset_member(s))
        if family == "lww":
            st = L.lwwmap_init(3, 8)
            for t in range(1, 16):
                st = L.lwwmap_put(st, np.uint32(rng.randrange(3)),
                                  np.uint32(rng.randrange(8)),
                                  np.uint32(rng.randrange(100)),
                                  np.uint32(t), np.bool_(rng.random() < .8))
            return st, L.lwwmap_join, lambda s: (
                np.asarray(s.val), np.asarray(s.live))
        st = L.mvregister_init(3, 3)
        for _ in range(10):
            st = L.mvregister_write(st, np.uint32(rng.randrange(3)),
                                    np.uint32(rng.randrange(1, 50)))
        return st, L.mvregister_join, lambda s: (
            np.asarray(s.val), np.asarray(s.live))

    def read_equal(x, y):
        fx, fy = read(x), read(y)
        if not isinstance(fx, tuple):
            fx, fy = (fx,), (fy,)
        return all(np.array_equal(np.asarray(u), np.asarray(v))
                   for u, v in zip(fx, fy))

    for _ in range(10):
        st, join, read = rand_state()
        rows = [jax.tree.map(lambda x: x[i], st) for i in range(3)]
        a, b, c = rows
        # idempotence
        aa = join(a, a)
        assert jax.tree.all(jax.tree.map(
            lambda x, y: bool(jnp.all(x == y)), aa, a))
        # commutativity on read: join(a,b) and join(b,a) agree on the
        # observable value (raw states may differ only where tie-break
        # metadata is symmetric anyway)
        assert read_equal(join(a, b), join(b, a))
        # associativity: (a+b)+c == a+(b+c)
        lhs = join(join(a, b), c)
        rhs = join(a, join(b, c))
        assert jax.tree.all(jax.tree.map(
            lambda x, y: bool(jnp.all(x == y)), lhs, rhs))


def test_gcounter_gossip_convergence_1k_replicas():
    """BASELINE config 2: 1K replicas, batched elementwise-max join,
    dissemination rounds to global agreement."""
    R = 1024
    A = 64
    counts = (jnp.arange(R, dtype=jnp.uint32)[:, None]
              * jnp.ones((1, A), jnp.uint32) % 7)
    st = L.GCounterState(
        counts=counts, actor=jnp.arange(R, dtype=jnp.uint32) % A)
    rounds = 0
    for off in gossip.dissemination_offsets(R):
        st = L.gossip_round(L.gcounter_join, st,
                            gossip.ring_perm(R, off))
        rounds += 1
    assert rounds == 10
    expected = np.asarray(counts).max(axis=0)
    assert (np.asarray(st.counts) == expected[None, :]).all()

# ---------------------------------------------------------------------------
# Model-merging joins (ROADMAP: weight merging as lattice joins)
# ---------------------------------------------------------------------------


def test_model_merging_joins_registered_with_law_subsets():
    """The analyzer-verified first step of the mesh-scale model-merging
    workload: all three strategies are in JOIN_REGISTRY with their
    HONEST law subsets (mean/weighted are not idempotent joins — they
    declare fewer laws via JoinSpec.laws, they do not skip the pass)."""
    reg = L.JOIN_REGISTRY
    assert reg["tensor_max"].laws == L.ALL_LAWS
    assert reg["tensor_mean"].laws == ("commutativity",)
    assert reg["weighted_mean"].laws == ("commutativity",
                                         "associativity")
    assert reg["weighted_mean"].atol > 0


def test_model_merging_joins_pass_their_declared_laws():
    from go_crdt_playground_tpu.analysis import lattice_laws

    for name in ("tensor_max", "tensor_mean", "weighted_mean"):
        findings, stats = lattice_laws.check_join_spec(
            L.JOIN_REGISTRY[name], seeds=(3, 4), n_rows=6, n_ops=20)
        assert not findings, [f.render() for f in findings]
        assert stats["laws_checked"] == 2 * len(
            L.JOIN_REGISTRY[name].laws)


def test_invalid_law_declaration_is_its_own_code():
    """A typo'd or empty law subset is a J004 registration error —
    never mislabeled as a commutativity counterexample, never a
    silent skip."""
    from go_crdt_playground_tpu.analysis import lattice_laws

    bad = L.JoinSpec("planted", lambda rng, n, ops: None,
                     lambda a, b: a, lambda s: {}, laws=("cmutativity",))
    findings, stats = lattice_laws.check_join_spec(bad, seeds=(1,))
    assert findings and findings[0].code == "J004"
    assert stats["laws_checked"] == 0
    empty = bad._replace(laws=())
    findings, _ = lattice_laws.check_join_spec(empty, seeds=(1,))
    assert findings and findings[0].code == "J004"


def test_tensor_max_gossip_converges():
    """The true-lattice strategy rides the existing gossip machinery:
    a ring dissemination drives every replica to the elementwise max."""
    R, D = 8, 16
    w = jnp.asarray(np.random.default_rng(0)
                    .normal(0, 1, (R, D)).astype(np.float32))
    st = L.TensorMergeState(w=w)
    for off in gossip.dissemination_offsets(R):
        st = L.gossip_round(L.tensor_max_join, st,
                            gossip.ring_perm(R, off))
    expected = np.asarray(w).max(axis=0)
    assert np.array_equal(np.asarray(st.w),
                          np.broadcast_to(expected, (R, D)))


def test_weighted_mean_value_is_order_free():
    """Σwx/Σw is the same whatever merge tree produced it — the
    property that makes weighted averaging shippable over gossip
    (under exactly-once contribution delivery)."""
    rng = np.random.default_rng(1)
    D = 8
    ws = rng.uniform(0.5, 2.0, 4)
    xs = rng.normal(0, 1, (4, D)).astype(np.float32)
    states = [L.WeightedMergeState(
        acc=jnp.asarray((w * x).astype(np.float32)[None]),
        weight=jnp.asarray(np.float32(w).reshape(1, 1)))
        for w, x in zip(ws, xs)]
    left = states[0]
    for s in states[1:]:
        left = L.weighted_mean_join(left, s)
    right = L.weighted_mean_join(
        L.weighted_mean_join(states[3], states[2]),
        L.weighted_mean_join(states[1], states[0]))
    expected = (ws[:, None] * xs).sum(0) / ws.sum()
    assert np.allclose(L.weighted_mean_value(left)[0], expected,
                       atol=1e-5)
    assert np.allclose(L.weighted_mean_value(right)[0], expected,
                       atol=1e-5)


def test_weighted_mean_join_is_not_idempotent_by_design():
    """Why the law subset excludes idempotence: join(a, a) double-
    counts every contribution — the documented exactly-once delivery
    contract (ops/lattices.py section comment)."""
    st = L.WeightedMergeState(acc=jnp.ones((1, 4), jnp.float32),
                              weight=jnp.ones((1, 1), jnp.float32))
    twice = L.weighted_mean_join(st, st)
    assert float(twice.weight[0, 0]) == 2.0  # not a lattice join
    # ... but the OBSERVABLE value is unchanged — self-merge corrupts
    # the accounting, not the average (why the paper can iterate)
    assert np.allclose(L.weighted_mean_value(twice),
                       L.weighted_mean_value(st))
