"""Conformance + property tests for the additional CRDT families:
tensor kernels vs the spec_extra oracles, randomized, plus lattice laws
(commutativity / associativity / idempotence) and gossip integration.
"""

import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from go_crdt_playground_tpu.models import spec_extra as S
from go_crdt_playground_tpu.ops import lattices as L
from go_crdt_playground_tpu.parallel import gossip


# ---------------------------------------------------------------------------
# G-Counter / PN-Counter
# ---------------------------------------------------------------------------


def test_gcounter_conformance_randomized():
    rng = random.Random(0)
    R = 4
    spec = [S.GCounter(i, R) for i in range(R)]
    st = L.gcounter_init(R, R)
    for _ in range(200):
        if rng.random() < 0.7:
            r = rng.randrange(R)
            amt = rng.randint(1, 5)
            spec[r].inc(amt)
            st = L.gcounter_inc(st, np.uint32(r), np.uint32(amt))
        else:
            d, s = rng.randrange(R), rng.randrange(R)
            spec[d].merge(spec[s])
            merged = L.gcounter_join(
                jax.tree.map(lambda x: x[d], st),
                jax.tree.map(lambda x: x[s], st))
            st = jax.tree.map(lambda f, row: f.at[d].set(row), st, merged)
        assert np.array_equal(
            np.asarray(st.counts),
            np.array([c.counts for c in spec], np.uint32))
    for r in range(R):
        assert int(L.gcounter_value(st)[r]) == spec[r].value()


def test_pncounter_conformance_randomized():
    rng = random.Random(1)
    R = 4
    spec = [S.PNCounter(i, R) for i in range(R)]
    st = L.pncounter_init(R, R)
    for _ in range(200):
        if rng.random() < 0.7:
            r = rng.randrange(R)
            amt = rng.randint(-5, 5)
            if amt >= 0:
                spec[r].inc(amt)
            else:
                spec[r].dec(-amt)
            st = L.pncounter_add(st, np.uint32(r), np.int32(amt))
        else:
            d, s = rng.randrange(R), rng.randrange(R)
            spec[d].merge(spec[s])
            merged = L.pncounter_join(
                jax.tree.map(lambda x: x[d], st),
                jax.tree.map(lambda x: x[s], st))
            st = jax.tree.map(lambda f, row: f.at[d].set(row), st, merged)
    vals = np.asarray(L.pncounter_value(st))
    for r in range(R):
        assert int(vals[r]) == spec[r].value()


# ---------------------------------------------------------------------------
# 2P-Set
# ---------------------------------------------------------------------------


def test_twopset_conformance_randomized():
    rng = random.Random(2)
    R, E = 3, 12
    universe = [f"k{i}" for i in range(E)]
    spec = [S.TwoPSet() for _ in range(R)]
    st = L.twopset_init(R, E)
    for _ in range(200):
        p = rng.random()
        r = rng.randrange(R)
        e = rng.randrange(E)
        if p < 0.5:
            spec[r].add(universe[e])
            st = L.twopset_add(st, np.uint32(r), np.uint32(e))
        elif p < 0.75:
            spec[r].del_(universe[e])
            st = L.twopset_del(st, np.uint32(r), np.uint32(e))
        else:
            d, s = rng.randrange(R), rng.randrange(R)
            spec[d].merge(spec[s])
            merged = L.twopset_join(
                jax.tree.map(lambda x: x[d], st),
                jax.tree.map(lambda x: x[s], st))
            st = jax.tree.map(lambda f, row: f.at[d].set(row), st, merged)
        member = np.asarray(L.twopset_member(st))
        for r2 in range(R):
            got = sorted(universe[i] for i in np.nonzero(member[r2])[0])
            assert got == spec[r2].values(), r2


def test_twopset_remove_wins_forever():
    st = L.twopset_init(2, 4)
    st = L.twopset_add(st, np.uint32(0), np.uint32(1))
    st = L.twopset_del(st, np.uint32(0), np.uint32(1))
    st = L.twopset_add(st, np.uint32(0), np.uint32(1))  # re-add is futile
    assert not bool(L.twopset_member(st)[0, 1])
    # unobserved delete is a no-op
    st = L.twopset_del(st, np.uint32(1), np.uint32(2))
    assert not bool(st.removed[1, 2])


# ---------------------------------------------------------------------------
# LWW-Map
# ---------------------------------------------------------------------------


def test_lwwmap_conformance_randomized():
    rng = random.Random(3)
    R, E = 3, 8
    universe = [f"k{i}" for i in range(E)]
    spec = [S.LWWMap(actor=i) for i in range(R)]
    st = L.lwwmap_init(R, E)
    ts = 0
    for _ in range(200):
        p = rng.random()
        r = rng.randrange(R)
        e = rng.randrange(E)
        if p < 0.55:
            ts += 1
            v = rng.randrange(1000)
            spec[r].put(universe[e], v, ts)
            st = L.lwwmap_put(st, np.uint32(r), np.uint32(e), np.uint32(v),
                              np.uint32(ts), np.bool_(True))
        elif p < 0.7:
            ts += 1
            spec[r].delete(universe[e], ts)
            st = L.lwwmap_put(st, np.uint32(r), np.uint32(e), np.uint32(0),
                              np.uint32(ts), np.bool_(False))
        else:
            d, s = rng.randrange(R), rng.randrange(R)
            spec[d].merge(spec[s])
            merged = L.lwwmap_join(
                jax.tree.map(lambda x: x[d], st),
                jax.tree.map(lambda x: x[s], st))
            st = jax.tree.map(lambda f, row: f.at[d].set(row), st, merged)
        for r2 in range(R):
            live = np.asarray(st.live[r2])
            vals = np.asarray(st.val[r2])
            got = {universe[i]: int(vals[i]) for i in np.nonzero(live)[0]}
            assert got == spec[r2].items(), r2


def test_lwwmap_concurrent_same_ts_actor_tiebreak():
    st = L.lwwmap_init(2, 2)
    st = L.lwwmap_put(st, np.uint32(0), np.uint32(0), np.uint32(10),
                      np.uint32(5), np.bool_(True))
    st = L.lwwmap_put(st, np.uint32(1), np.uint32(0), np.uint32(20),
                      np.uint32(5), np.bool_(True))
    # merge both directions: higher actor (1) must win deterministically
    a = L.lwwmap_join(jax.tree.map(lambda x: x[0], st),
                      jax.tree.map(lambda x: x[1], st))
    b = L.lwwmap_join(jax.tree.map(lambda x: x[1], st),
                      jax.tree.map(lambda x: x[0], st))
    assert int(a.val[0]) == int(b.val[0]) == 20


# ---------------------------------------------------------------------------
# MV-Register
# ---------------------------------------------------------------------------


def test_mvregister_conformance_randomized():
    rng = random.Random(4)
    R = 4
    spec = [S.MVRegister(i, R) for i in range(R)]
    st = L.mvregister_init(R, R)
    for step in range(300):
        if rng.random() < 0.5:
            r = rng.randrange(R)
            v = rng.randrange(1, 1000)
            spec[r].write(v)
            st = L.mvregister_write(st, np.uint32(r), np.uint32(v))
        else:
            d, s = rng.randrange(R), rng.randrange(R)
            spec[d].merge(spec[s])
            merged = L.mvregister_join(
                jax.tree.map(lambda x: x[d], st),
                jax.tree.map(lambda x: x[s], st))
            st = jax.tree.map(lambda f, row: f.at[d].set(row), st, merged)
        for r2 in range(R):
            for name, arr in (("ctx", st.ctx), ("live", st.live),
                              ("cnt", st.cnt), ("val", st.val)):
                assert np.asarray(arr[r2]).tolist() == list(
                    getattr(spec[r2], name)), (step, r2, name)


def test_mvregister_concurrent_writes_both_visible():
    st = L.mvregister_init(2, 2)
    st = L.mvregister_write(st, np.uint32(0), np.uint32(7))
    st = L.mvregister_write(st, np.uint32(1), np.uint32(9))
    merged = L.mvregister_join(jax.tree.map(lambda x: x[0], st),
                               jax.tree.map(lambda x: x[1], st))
    vis = sorted(int(v) for v, l in zip(np.asarray(merged.val),
                                        np.asarray(merged.live)) if l)
    assert vis == [7, 9]
    # a subsequent write dominates both
    st2 = jax.tree.map(lambda f, row: f.at[0].set(row), st, merged)
    st2 = L.mvregister_write(st2, np.uint32(0), np.uint32(42))
    back = L.mvregister_join(jax.tree.map(lambda x: x[1], st2),
                             jax.tree.map(lambda x: x[0], st2))
    vis2 = [int(v) for v, l in zip(np.asarray(back.val),
                                   np.asarray(back.live)) if l]
    assert vis2 == [42]


# ---------------------------------------------------------------------------
# OR-Map
# ---------------------------------------------------------------------------


def test_ormap_conformance_randomized():
    rng = random.Random(6)
    R, E = 3, 8
    universe = [f"k{i}" for i in range(E)]
    spec = [S.ORMap(actor=i, num_actors=R) for i in range(R)]
    st = L.ormap_init(R, E, R)
    ts = 0
    for step in range(200):
        p = rng.random()
        r = rng.randrange(R)
        e = rng.randrange(E)
        if p < 0.5:
            ts += 1
            v = rng.randrange(1, 1000)
            spec[r].put(universe[e], v, ts)
            st = L.ormap_put(st, np.uint32(r), np.uint32(e), np.uint32(v),
                             np.uint32(ts))
        elif p < 0.7:
            spec[r].delete(universe[e])
            st = L.ormap_delete(st, np.uint32(r), np.uint32(e))
        else:
            d, s = rng.randrange(R), rng.randrange(R)
            spec[d].merge(spec[s])
            merged = L.ormap_join(
                jax.tree.map(lambda x: x[d], st),
                jax.tree.map(lambda x: x[s], st))
            st = jax.tree.map(lambda f, row: f.at[d].set(row), st, merged)
        for r2 in range(R):
            pres = np.asarray(st.present[r2])
            vals = np.asarray(st.val[r2])
            got = {universe[i]: int(vals[i]) for i in np.nonzero(pres)[0]}
            assert got == spec[r2].items(), (step, r2)


def test_ormap_concurrent_put_wins_over_delete():
    """The key membership inherits AWSet add-wins (awset_test.go:85-122's
    property lifted to maps)."""
    spec = [S.ORMap(actor=i, num_actors=2) for i in range(2)]
    st = L.ormap_init(2, 4, 2)
    spec[0].put("k", 1, 1)
    st = L.ormap_put(st, np.uint32(0), np.uint32(0), np.uint32(1), np.uint32(1))
    spec[1].merge(spec[0])
    m = L.ormap_join(jax.tree.map(lambda x: x[1], st),
                     jax.tree.map(lambda x: x[0], st))
    st = jax.tree.map(lambda f, row: f.at[1].set(row), st, m)
    # concurrent: replica 0 deletes, replica 1 re-puts
    spec[0].delete("k"); spec[1].put("k", 7, 2)
    st = L.ormap_delete(st, np.uint32(0), np.uint32(0))
    st = L.ormap_put(st, np.uint32(1), np.uint32(0), np.uint32(7), np.uint32(2))
    spec[0].merge(spec[1])
    m = L.ormap_join(jax.tree.map(lambda x: x[0], st),
                     jax.tree.map(lambda x: x[1], st))
    assert bool(m.present[0])       # writer wins
    assert int(m.val[0]) == 7
    assert spec[0].get("k") == 7


# ---------------------------------------------------------------------------
# Lattice laws + gossip integration
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("family", ["gcounter", "twopset", "lww", "mvreg"])
def test_lattice_laws(family):
    """Idempotence, commutativity(-on-read), associativity on random
    states."""
    rng = random.Random(5)

    def rand_state():
        if family == "gcounter":
            st = L.gcounter_init(3, 3)
            for _ in range(10):
                st = L.gcounter_inc(st, np.uint32(rng.randrange(3)),
                                    np.uint32(rng.randint(1, 9)))
            return st, L.gcounter_join, lambda s: np.asarray(s.counts)
        if family == "twopset":
            st = L.twopset_init(3, 8)
            for _ in range(15):
                f = L.twopset_add if rng.random() < 0.6 else L.twopset_del
                st = f(st, np.uint32(rng.randrange(3)),
                       np.uint32(rng.randrange(8)))
            return st, L.twopset_join, lambda s: np.asarray(
                L.twopset_member(s))
        if family == "lww":
            st = L.lwwmap_init(3, 8)
            for t in range(1, 16):
                st = L.lwwmap_put(st, np.uint32(rng.randrange(3)),
                                  np.uint32(rng.randrange(8)),
                                  np.uint32(rng.randrange(100)),
                                  np.uint32(t), np.bool_(rng.random() < .8))
            return st, L.lwwmap_join, lambda s: (
                np.asarray(s.val), np.asarray(s.live))
        st = L.mvregister_init(3, 3)
        for _ in range(10):
            st = L.mvregister_write(st, np.uint32(rng.randrange(3)),
                                    np.uint32(rng.randrange(1, 50)))
        return st, L.mvregister_join, lambda s: (
            np.asarray(s.val), np.asarray(s.live))

    def read_equal(x, y):
        fx, fy = read(x), read(y)
        if not isinstance(fx, tuple):
            fx, fy = (fx,), (fy,)
        return all(np.array_equal(np.asarray(u), np.asarray(v))
                   for u, v in zip(fx, fy))

    for _ in range(10):
        st, join, read = rand_state()
        rows = [jax.tree.map(lambda x: x[i], st) for i in range(3)]
        a, b, c = rows
        # idempotence
        aa = join(a, a)
        assert jax.tree.all(jax.tree.map(
            lambda x, y: bool(jnp.all(x == y)), aa, a))
        # commutativity on read: join(a,b) and join(b,a) agree on the
        # observable value (raw states may differ only where tie-break
        # metadata is symmetric anyway)
        assert read_equal(join(a, b), join(b, a))
        # associativity: (a+b)+c == a+(b+c)
        lhs = join(join(a, b), c)
        rhs = join(a, join(b, c))
        assert jax.tree.all(jax.tree.map(
            lambda x, y: bool(jnp.all(x == y)), lhs, rhs))


def test_gcounter_gossip_convergence_1k_replicas():
    """BASELINE config 2: 1K replicas, batched elementwise-max join,
    dissemination rounds to global agreement."""
    R = 1024
    A = 64
    counts = (jnp.arange(R, dtype=jnp.uint32)[:, None]
              * jnp.ones((1, A), jnp.uint32) % 7)
    st = L.GCounterState(
        counts=counts, actor=jnp.arange(R, dtype=jnp.uint32) % A)
    rounds = 0
    for off in gossip.dissemination_offsets(R):
        st = L.gossip_round(L.gcounter_join, st,
                            gossip.ring_perm(R, off))
        rounds += 1
    assert rounds == 10
    expected = np.asarray(counts).max(axis=0)
    assert (np.asarray(st.counts) == expected[None, :]).all()