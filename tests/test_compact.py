"""Fixed-K compact δ payloads (ops/compact.py): roundtrip fidelity,
overflow safety, and the compact gossip rounds (including the ICI ring
that ships only O(K) bytes per replica)."""

import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from go_crdt_playground_tpu.models import awset_delta
from go_crdt_playground_tpu.ops import compact as compact_ops
from go_crdt_playground_tpu.ops import delta as delta_ops
from go_crdt_playground_tpu.parallel import collectives, gossip
from go_crdt_playground_tpu.parallel import mesh as mesh_mod


def _random_delta_state(rng, R=8, E=32, A=8):
    st = awset_delta.init(R, E, A)
    for _ in range(6 * R):
        r = rng.randrange(R)
        e = rng.randrange(E)
        if rng.random() < 0.75:
            st = awset_delta.add_element(st, np.uint32(r), np.uint32(e))
        else:
            sel = np.zeros(E, bool)
            sel[e] = True
            st = awset_delta.del_elements(st, np.uint32(r), np.asarray(sel))
    return st


def _payload_fields_equal(a, b):
    for name in a._fields:
        assert np.array_equal(np.asarray(getattr(a, name)),
                              np.asarray(getattr(b, name))), name


def test_compact_expand_roundtrip_when_fits():
    rng = random.Random(43)
    st = _random_delta_state(rng)
    E = st.present.shape[-1]
    src = jax.tree.map(lambda x: x[gossip.ring_perm(8, 1)], st)
    payload = jax.vmap(delta_ops.delta_extract)(src, st.vv)
    comp = compact_ops.compact_payload_batch(payload, E, E)  # K = E: fits
    assert not bool(comp.overflow.any())
    back = compact_ops.expand_payload_batch(comp, E)
    _payload_fields_equal(payload, back)


def test_compact_wire_bytes_are_o_k_not_o_e():
    rng = random.Random(47)
    st = _random_delta_state(rng, E=512)
    src = jax.tree.map(lambda x: x[gossip.ring_perm(8, 1)], st)
    payload = jax.vmap(delta_ops.delta_extract)(src, st.vv)
    comp = compact_ops.compact_payload_batch(payload, 16, 16)
    one_dense = jax.tree.map(lambda x: x[0], payload)
    one_comp = jax.tree.map(lambda x: x[0], comp)
    assert one_comp.nbytes_wire() < one_dense.nbytes_dense() / 4


def test_overflow_truncates_and_masks_clock():
    rng = random.Random(53)
    st = _random_delta_state(rng)
    E = st.present.shape[-1]
    src = jax.tree.map(lambda x: x[gossip.ring_perm(8, 1)], st)
    payload = jax.vmap(delta_ops.delta_extract)(src, st.vv)
    counts = np.asarray(payload.changed.sum(axis=-1))
    k = int(counts.max()) - 1
    assert k >= 1
    comp = compact_ops.compact_payload_batch(payload, k, E)
    over = np.asarray(comp.overflow)
    # mixed coverage: the max-count row(s) overflow at k = max-1, rows
    # with smaller payloads don't (the seeded fixture guarantees spread)
    assert over.any() and not over.all(), counts
    # truncated rows must not advance the receiver clock (vv zeroed)
    assert (np.asarray(comp.src_vv)[over] == 0).all()
    back = compact_ops.expand_payload_batch(comp, E)
    # claimed lanes are a subset of the dense payload with equal dots
    ch = np.asarray(back.changed)
    assert (ch <= np.asarray(payload.changed)).all()
    assert (ch.sum(axis=-1) <= k).all()
    where = ch.nonzero()
    assert np.array_equal(np.asarray(back.ch_da)[where],
                          np.asarray(payload.ch_da)[where])


def test_compact_round_matches_dense_delta_round_steady_state():
    """After a dense bootstrap round (the full-merge analogue of
    awset-delta_test.go:53-56), compact rounds with adequate K are
    bitwise the dense v2 δ rounds."""
    rng = random.Random(59)
    st = _random_delta_state(rng)
    R, E = st.present.shape
    st = gossip.delta_gossip_round(st, gossip.ring_perm(R, 1),
                                   delta_semantics="v2")
    for off in (2, 1, 4):
        perm = gossip.ring_perm(R, off)
        dense = gossip.delta_gossip_round(st, perm, delta_semantics="v2")
        comp = gossip.compact_delta_gossip_round(st, perm, E, E)
        for name in dense._fields:
            assert np.array_equal(np.asarray(getattr(dense, name)),
                                  np.asarray(getattr(comp, name))), \
                (off, name)
        st = dense


def test_tiny_k_rounds_are_safe_and_dense_rounds_complete():
    """Overflowed compact rounds are lossy-but-safe: membership keeps
    its invariants and a dense schedule afterwards still converges to
    the same fixed point as a pure-dense run."""
    rng = random.Random(61)
    st = _random_delta_state(rng)
    R = st.present.shape[0]
    lossy = st
    for off in (1, 2, 4, 1):
        lossy = gossip.compact_delta_gossip_round(
            lossy, gossip.ring_perm(R, off), 2, 2)
    # dense completion from the lossy state
    done = gossip.all_pairs_converge(lossy, delta=True,
                                     delta_semantics="v2")
    ref = gossip.all_pairs_converge(st, delta=True, delta_semantics="v2")
    assert bool(collectives.converged(done.present, done.vv))
    assert np.array_equal(np.asarray(done.present), np.asarray(ref.present))
    assert np.array_equal(np.asarray(done.vv), np.asarray(ref.vv))


def test_compact_ring_shardmap_matches_jit_round():
    rng = random.Random(67)
    st = _random_delta_state(rng, R=16, E=32, A=16)
    m = mesh_mod.make_mesh((8, 1))
    sharded = mesh_mod.shard_state(st, m)
    ring = gossip.compact_ring_round_shardmap(sharded, m, 32, 32)
    # ring: device i's block -> i+1, i.e. replica r absorbs r - shard_size
    perm = (jnp.arange(16, dtype=jnp.uint32) - 2) % 16
    expected = gossip.compact_delta_gossip_round(st, perm, 32, 32)
    for name in ring._fields:
        assert np.array_equal(np.asarray(getattr(ring, name)),
                              np.asarray(getattr(expected, name))), name


def test_compact_ring_rejects_sharded_element_axis():
    rng = random.Random(71)
    st = _random_delta_state(rng, R=8, E=32, A=8)
    m = mesh_mod.make_mesh((4, 2))
    with pytest.raises(ValueError):
        gossip.compact_ring_round_shardmap(st, m)
