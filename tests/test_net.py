"""Networked anti-entropy (net/peer.py): the reference's simulated
``dst.Merge(src)`` exchange (awset_test.go:16-17) carried over a real TCP
socket in the compact δ wire format, applied with the same kernels as the
on-chip gossip path.

Oracle: the executable spec (models/spec.py).  One push-pull ``sync_with``
equals the sequential spec exchange ``server.merge(client)`` then
``client.merge(server)`` — the server extracts its reply after absorbing
the client's payload.
"""

import time

import numpy as np
import pytest

from go_crdt_playground_tpu.models.spec import AWSetDelta, VersionVector
from go_crdt_playground_tpu.net import Node, framing
from go_crdt_playground_tpu.net.framing import MODE_DELTA, MODE_FULL

E = 32
A = 2


def make_nodes(delta_semantics="v2", num_actors=A):
    nodes = [Node(i, E, num_actors, delta_semantics=delta_semantics)
             for i in range(num_actors)]
    return nodes


def key(i: int) -> str:
    return f"e{i:03d}"


def make_spec_pair(delta_semantics="v2", num_actors=A):
    return [AWSetDelta(actor=i,
                       version_vector=VersionVector([0] * num_actors),
                       delta_semantics=delta_semantics)
            for i in range(num_actors)]


def spec_exchange(client: AWSetDelta, server: AWSetDelta) -> None:
    server.merge(client)
    client.merge(server)


def members_of(spec: AWSetDelta):
    return np.asarray(sorted(int(k[1:]) for k in spec.entries))


def test_two_node_convergence_and_modes():
    a, b = make_nodes()
    with b:
        addr = b.serve()
        a.add(1, 2, 3)
        b.add(3, 4)
        stats = a.sync_with(addr)
        # neither side had seen the other: both directions ship FULL state
        assert stats.mode_sent == MODE_FULL
        assert stats.mode_received == MODE_FULL
        np.testing.assert_array_equal(a.members(), [1, 2, 3, 4])
        np.testing.assert_array_equal(b.members(), [1, 2, 3, 4])
        # established peers ride the δ path
        a.add(5)
        stats = a.sync_with(addr)
        assert stats.mode_sent == MODE_DELTA
        assert stats.mode_received == MODE_DELTA
        np.testing.assert_array_equal(b.members(), [1, 2, 3, 4, 5])


def test_add_wins_over_concurrent_delete():
    # the reference's headline property (awset_test.go:85-112) over a socket
    a, b = make_nodes()
    with b:
        addr = b.serve()
        a.add(5)
        a.sync_with(addr)
        b.delete(5)       # observed remove of the first instance...
        a.add(5)          # ...concurrent with a fresh add at A
        a.sync_with(addr)
        np.testing.assert_array_equal(a.members(), [5])
        np.testing.assert_array_equal(b.members(), [5])


def test_observed_delete_sticks():
    # the non-concurrent case (awset_test.go:113-121): B observed the add
    # and deleted it; no concurrent re-add, so the delete wins everywhere
    a, b = make_nodes()
    with b:
        addr = b.serve()
        a.add(7)
        a.sync_with(addr)
        b.delete(7)
        a.sync_with(addr)
        assert a.members().size == 0
        assert b.members().size == 0


def test_three_node_transitive_propagation():
    nodes = make_nodes(num_actors=3)
    a, b, c = nodes
    with a, b, c:
        addr_b = b.serve()
        addr_c = c.serve()
        a.add(1)
        c.add(9)
        a.sync_with(addr_b)    # B learns 1
        b.sync_with(addr_c)    # C learns 1 via B; B learns 9
        b.sync_with(addr_c)    # (already converged pair — stays converged)
        a.sync_with(addr_b)    # A learns 9 via B
        for n in (a, b, c):
            np.testing.assert_array_equal(n.members(), [1, 9])


def test_payload_bytes_shrink_after_convergence():
    a, b = make_nodes()
    with b:
        addr = b.serve()
        a.add(*range(20))
        b.add(30)  # tick B's clock so the δ dispatch applies both ways
        first = a.sync_with(addr)
        second = a.sync_with(addr)
        # converged: both directions are near-empty δ payloads (only
        # HELLO + framing + empty sections remain on the wire)
        assert second.mode_sent == MODE_DELTA
        assert second.mode_received == MODE_DELTA
        assert second.bytes_sent < first.bytes_sent
        assert second.bytes_received < first.bytes_received
        assert second.bytes_sent < 48


def test_write_free_replica_keeps_full_dispatch():
    """A replica that never wrote has counter 0 — peers must keep taking
    the full-merge branch toward it (awset-delta_test.go:53)."""
    a, b = make_nodes()
    with b:
        addr = b.serve()
        a.add(1)
        stats = a.sync_with(addr)
        assert stats.mode_received == MODE_FULL
        stats = a.sync_with(addr)
        # B still has never written: its reply stays FULL; A has written,
        # so A's outbound flips to δ after the first exchange
        assert stats.mode_sent == MODE_DELTA
        assert stats.mode_received == MODE_FULL


def test_dimension_mismatch_rejected():
    a = Node(0, E, A)
    b = Node(1, E * 2, A)
    with b:
        addr = b.serve()
        with pytest.raises(framing.RemoteError, match="universe mismatch"):
            a.sync_with(addr)


def test_actor_axis_mismatch_rejected():
    # wire-layer ValueError must surface as a clean MSG_ERROR frame, not
    # kill the server handler thread
    a = Node(0, E, 2)
    b = Node(1, E, 3)
    with b:
        addr = b.serve()
        with pytest.raises(framing.RemoteError, match="actor-axis mismatch"):
            a.sync_with(addr)
        # server survives the bad peer and still serves well-formed ones
        c = Node(0, E, 3)
        c.add(4)
        c.sync_with(addr)
        np.testing.assert_array_equal(b.members(), [4])


@pytest.mark.parametrize("delta_semantics", ["v2", "reference"])
def test_randomized_scenario_matches_spec(delta_semantics):
    """Random op/sync interleavings over the socket must track the spec
    replica pair step for step (membership oracle; VVs compared too in the
    non-quirk v2 mode)."""
    rng = np.random.default_rng(7)
    a, b = make_nodes(delta_semantics)
    sa, sb = make_spec_pair(delta_semantics)
    with b:
        addr = b.serve()
        for _ in range(60):
            op = rng.integers(0, 4)
            if op == 0:
                ids = rng.choice(E, size=rng.integers(1, 4), replace=False)
                a.add(*ids)
                sa.add(*(key(i) for i in ids))
            elif op == 1:
                ids = rng.choice(E, size=rng.integers(1, 4), replace=False)
                b.add(*ids)
                sb.add(*(key(i) for i in ids))
            elif op == 2:
                who, spec_who = (a, sa) if rng.integers(2) else (b, sb)
                live = who.members()
                if live.size:
                    ids = rng.choice(live, size=rng.integers(
                        1, min(3, live.size) + 1), replace=False)
                    who.delete(*ids)
                    spec_who.del_(*(key(i) for i in ids))
            else:
                a.sync_with(addr)
                spec_exchange(sa, sb)
                np.testing.assert_array_equal(a.members(), members_of(sa))
                np.testing.assert_array_equal(b.members(), members_of(sb))
        a.sync_with(addr)
        spec_exchange(sa, sb)
        np.testing.assert_array_equal(a.members(), members_of(sa))
        np.testing.assert_array_equal(b.members(), members_of(sb))
        if delta_semantics == "v2":
            np.testing.assert_array_equal(
                a.vv(), [sa.version_vector[i] for i in range(A)])
            np.testing.assert_array_equal(
                b.vv(), [sb.version_vector[i] for i in range(A)])


def test_recorder_counts_exchanges():
    from go_crdt_playground_tpu.obs import Recorder

    ra, rb = Recorder(), Recorder()
    a = Node(0, E, A, recorder=ra)
    b = Node(1, E, A, recorder=rb)
    with b:
        addr = b.serve()
        a.add(1)
        stats = a.sync_with(addr)
        ca = ra.snapshot()["counters"]
        # sync_with returns when the client has the reply; the server's
        # handler thread records its counters after its send returns —
        # poll instead of racing it.
        deadline = time.monotonic() + 5.0
        while ("sync.exchanges" not in rb.snapshot()["counters"]
               and time.monotonic() < deadline):
            time.sleep(0.01)
        cb = rb.snapshot()["counters"]
        assert ca["sync.exchanges"] == 1 and cb["sync.exchanges"] == 1
        assert ca["sync.bytes_sent"] == stats.bytes_sent
        assert ca["sync.bytes_received"] == stats.bytes_received
        # server's sent bytes are the client's received bytes
        assert cb["sync.bytes_sent"] == stats.bytes_received
        assert ca["sync.full_payloads"] == 1  # first contact ships FULL


def test_frame_size_matches_send():
    assert framing.frame_size(0) == 4
    assert framing.frame_size(127) == 4 + 127
    assert framing.frame_size(128) == 5 + 128
    assert framing.frame_size(1 << 20) == 2 + 1 + 3 + (1 << 20)


def test_soak_concurrent_clients_bounded_threads():
    """32 concurrent clients x repeated sync_with against one server node:
    every exchange converges (the reference's per-replica isolation,
    awset_test.go:159-168, held under real concurrency) and the server's
    connection-thread population stays bounded by MAX_CONNS."""
    import threading

    n_clients, n_rounds = 32, 4
    num_actors = n_clients + 1
    e_soak = 64  # the universe must hold one element per participant
    server = Node(0, e_soak, num_actors)
    clients = [Node(i + 1, e_soak, num_actors) for i in range(n_clients)]
    errors = []
    peak_threads = [threading.active_count()]

    with server:
        addr = server.serve()
        server.add(0)

        def run(i, node):
            try:
                node.add(i + 1)
                for _ in range(n_rounds):
                    node.sync_with(addr)
                    peak_threads[0] = max(peak_threads[0],
                                          threading.active_count())
            except Exception as e:  # noqa: BLE001
                errors.append((i, e))

        threads = [threading.Thread(target=run, args=(i, c))
                   for i, c in enumerate(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors[:3]
        # the server absorbed every client's element
        assert set(server.members()) == set(range(n_clients + 1))
        # one final pull so every client sees the fully-merged server
        for c in clients:
            c.sync_with(addr)
        for c in clients:
            assert set(c.members()) == set(range(n_clients + 1))
    # baseline + 32 client threads + server accept/conn threads; the cap
    # keeps connection threads <= MAX_CONNS even under the burst
    assert peak_threads[0] <= threading.active_count() + n_clients \
        + server.MAX_CONNS + 8


def test_server_sheds_connections_at_capacity():
    """At max_conns the accept loop closes new dials instead of queueing
    (a shed exchange is a lost gossip round, which anti-entropy heals)."""
    import socket as socket_mod

    server = Node(0, E, A, max_conns=1, conn_timeout_s=5.0)
    with server:
        addr = server.serve()
        # occupy the single slot with a half-open connection
        hog = socket_mod.create_connection(addr, timeout=5.0)
        try:
            time.sleep(0.1)  # let the handler thread claim the slot
            # the next dial must be shed: the server closes it without a
            # byte, so the client's recv sees EOF quickly
            probe = socket_mod.create_connection(addr, timeout=5.0)
            with probe:
                probe.settimeout(5.0)
                assert probe.recv(1) == b""  # closed, not served
        finally:
            hog.close()
        # slot released: a real exchange works again
        peer = Node(1, E, A)
        peer.add(3)
        deadline = time.monotonic() + 10.0
        while True:
            try:
                peer.sync_with(addr)
                break
            except (OSError, framing.ProtocolError):
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.05)
        assert 3 in server.members()


def test_half_open_dial_releases_slot_at_hello_deadline():
    """An idle half-open dial must free its connection slot after the
    (short) HELLO deadline, not the full payload timeout — otherwise
    max_conns silent dials shed every legitimate gossip exchange for
    conn_timeout_s (ADVICE r3)."""
    import socket as socket_mod

    server = Node(0, E, A, max_conns=1, conn_timeout_s=30.0,
                  hello_timeout_s=0.5)
    with server:
        addr = server.serve()
        hog = socket_mod.create_connection(addr, timeout=5.0)
        try:
            time.sleep(0.8)  # past the HELLO deadline, far below 30s
            peer = Node(1, E, A)
            peer.add(5)
            # must succeed promptly: the hog's slot was reclaimed at the
            # HELLO deadline even though conn_timeout_s is 30s
            deadline = time.monotonic() + 10.0
            while True:
                try:
                    peer.sync_with(addr)
                    break
                except (OSError, framing.ProtocolError):
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.05)
            assert 5 in server.members()
        finally:
            hog.close()


def test_trickling_dial_releases_slot_at_hello_deadline():
    """The HELLO deadline is absolute for the whole frame: a dialer
    feeding one byte per timeout window must not hold a slot past it
    (per-recv socket timeouts alone would reset on every byte)."""
    import socket as socket_mod

    import threading

    server = Node(0, E, A, max_conns=1, conn_timeout_s=30.0,
                  hello_timeout_s=0.5)
    with server:
        addr = server.serve()
        hog = socket_mod.create_connection(addr, timeout=5.0)
        stop = threading.Event()

        def trickle():
            # valid frame prefix, one byte at a time, forever
            for b in framing.MAGIC * 1000:
                if stop.is_set():
                    return
                try:
                    hog.sendall(bytes([b]))
                except OSError:
                    return
                time.sleep(0.3)

        t = threading.Thread(target=trickle, daemon=True)
        t.start()
        try:
            time.sleep(1.0)  # several trickled bytes, past the deadline
            peer = Node(1, E, A)
            peer.add(7)
            deadline = time.monotonic() + 10.0
            while True:
                try:
                    peer.sync_with(addr)
                    break
                except (OSError, framing.ProtocolError):
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.05)
            assert 7 in server.members()
        finally:
            stop.set()
            hog.close()


def test_hello_timeout_ctor_param_clamped():
    """hello_timeout_s is a constructor parameter (not an attribute to
    poke) and can never exceed conn_timeout_s — the HELLO deadline
    exists to undercut the payload deadline (ADVICE r4)."""
    n = Node(0, E, A, hello_timeout_s=7.0, conn_timeout_s=3.0)
    assert n.hello_timeout_s == 3.0
    n = Node(0, E, A, hello_timeout_s=0.25)
    assert n.hello_timeout_s == 0.25
    assert Node(0, E, A).hello_timeout_s == Node.HELLO_TIMEOUT_S


def test_recv_exact_restores_socket_timeout():
    """A deadline passed to _recv_exact mutates the socket timeout per
    recv; the restore must live in _recv_exact itself so DIRECT callers
    (not just recv_frame) cannot leak a shortened timeout onto the
    socket (ADVICE r4)."""
    import socket as socket_mod

    a, b = socket_mod.socketpair()
    try:
        a.settimeout(12.5)
        b.sendall(b"xyz")
        assert framing._recv_exact(a, 3, time.monotonic() + 5.0) == b"xyz"
        assert a.gettimeout() == 12.5
        # the raising path restores too
        with pytest.raises(socket_mod.timeout):
            framing._recv_exact(a, 1, time.monotonic() - 1.0)
        assert a.gettimeout() == 12.5
    finally:
        a.close()
        b.close()
