"""Fused ingest+δ (ops/ingest.ingest_rows_delta + the Pallas twin):
bitwise pins against the seed two-pass path — apply via
``ingest_rows``, then a separate ``delta_extract`` — across
occupancies, padding rows, and the empty batch (the ISSUE-8 pin, same
style as the batch-vs-sequential pin in tests/test_serve.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from go_crdt_playground_tpu.models import awset_delta
from go_crdt_playground_tpu.ops import compact as compact_ops
from go_crdt_playground_tpu.ops import delta as delta_ops
from go_crdt_playground_tpu.ops import ingest as ingest_ops
from go_crdt_playground_tpu.ops.pallas_ingest import pallas_ingest_rows_delta

E, A = 72, 5


def _seeded_row(seed: int, warm_batches: int = 2):
    """A single-replica slice with history: adds, deletes, and a few
    foreign dots merged in (so δ extraction sees non-self actors)."""
    rng = np.random.default_rng(seed)
    st = awset_delta.init(1, E, A, actors=np.asarray([2], np.uint32))
    row = jax.tree.map(lambda x: x[0], st)
    for _ in range(warm_batches):
        row = ingest_ops.ingest_rows(
            row, jnp.asarray(rng.random((3, E)) < 0.25),
            jnp.asarray(rng.random((3, E)) < 0.15),
            jnp.ones(3, bool))
    # merge one foreign replica's state in (actor 0's dots land here)
    other = awset_delta.init(1, E, A, actors=np.asarray([0], np.uint32))
    orow = jax.tree.map(lambda x: x[0], other)
    orow = ingest_ops.ingest_rows(
        orow, jnp.asarray(rng.random((2, E)) < 0.2),
        jnp.asarray(rng.random((2, E)) < 0.1), jnp.ones(2, bool))
    payload = delta_ops.delta_extract(orow, row.vv)
    return delta_ops.delta_apply(row, payload, "v2")


def _batch(seed: int, b: int, density: float, live_pattern: str):
    rng = np.random.default_rng(seed)
    add = rng.random((b, E)) < density
    dl = rng.random((b, E)) < density / 2
    if live_pattern == "all":
        live = np.ones(b, bool)
    elif live_pattern == "none":
        live = np.zeros(b, bool)
    else:  # holes: padding rows interleaved with live ones
        live = (np.arange(b) % 3) != 1
    return add, dl, live


def _assert_trees_equal(got, want, label):
    for name in want._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(got, name)), np.asarray(getattr(want, name)),
            err_msg=f"{label}:{name}")


CASES = [
    (8, 0.15, "all"),      # typical occupancy
    (8, 0.15, "holes"),    # padding rows interleaved
    (8, 0.0, "all"),       # live rows, empty selectors (no-op ticks)
    (4, 0.9, "all"),       # dense batch (compact overflow at small K)
    (1, 0.2, "all"),       # single op
    (6, 0.2, "none"),      # all-padding batch
    (0, 0.0, "all"),       # empty batch axis
]


@pytest.mark.parametrize("b,density,live_pattern", CASES)
@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_fused_matches_two_pass_bitwise(b, density, live_pattern, impl):
    """State AND payload of the fused path are bitwise the seed
    two-pass result, per occupancy/padding/empty-batch case."""
    row = _seeded_row(11)
    add, dl, live = _batch(29 + b, b, density, live_pattern)
    pre_vv = row.vv

    want_state = ingest_ops.ingest_rows(
        row, jnp.asarray(add), jnp.asarray(dl), jnp.asarray(live))
    want_payload = delta_ops.delta_extract(want_state, pre_vv)

    fn = (ingest_ops.ingest_rows_delta if impl == "xla"
          else pallas_ingest_rows_delta)
    got_state, got_payload, compact = fn(
        row, jnp.asarray(add), jnp.asarray(dl), jnp.asarray(live),
        k_changed=16, k_deleted=16)

    _assert_trees_equal(got_state, want_state, f"{impl}-state")
    _assert_trees_equal(got_payload, want_payload, f"{impl}-payload")
    # the compact form is the payload through ops/compact.py, verbatim
    want_compact = compact_ops.compact_payload(want_payload, 16, 16)
    _assert_trees_equal(compact, want_compact, f"{impl}-compact")


def test_compact_form_roundtrips_when_it_fits():
    """Non-overflow compact δ expands back to the dense payload
    bitwise — the WAL-record equivalence the replay path relies on."""
    row = _seeded_row(13)
    add, dl, live = _batch(31, 6, 0.05, "all")
    _, payload, compact = ingest_ops.ingest_rows_delta(
        row, jnp.asarray(add), jnp.asarray(dl), jnp.asarray(live),
        k_changed=64, k_deleted=64)
    assert not bool(compact.overflow)
    back = compact_ops.expand_payload(compact, E)
    _assert_trees_equal(back, payload, "roundtrip")


def test_overflow_flag_fires_and_dense_stays_authoritative():
    """A δ claiming more lanes than K sets overflow; the dense payload
    returned alongside is complete (the fallback record source)."""
    row = _seeded_row(17)
    add, dl, live = _batch(37, 8, 0.9, "all")
    _, payload, compact = ingest_ops.ingest_rows_delta(
        row, jnp.asarray(add), jnp.asarray(dl), jnp.asarray(live),
        k_changed=4, k_deleted=4)
    assert bool(compact.overflow)
    assert int(np.asarray(payload.changed).sum()) > 4
    # overflow neutralizes the compact vv (ops/compact.py contract);
    # the dense payload keeps the real one
    assert np.asarray(compact.src_vv).sum() == 0
    assert np.asarray(payload.src_vv).sum() > 0


def test_pallas_twin_covers_uncovered_preexisting_lanes():
    """δ extraction vs the PRE-batch vv must also ship pre-existing
    lanes whose dots the pre-batch vv never covered (the
    compact-overflow gossip path leaves those; the two-pass path
    shipped them and the fused paths must too)."""
    row = _seeded_row(19)
    # graft a foreign dot the vv does NOT cover (overflowed-compact
    # apply shape: data landed, clock never advanced)
    row = row._replace(
        present=row.present.at[7].set(True),
        dot_actor=row.dot_actor.at[7].set(jnp.uint32(4)),
        dot_counter=row.dot_counter.at[7].set(jnp.uint32(90)))
    add = np.zeros((2, E), bool)
    add[0, 3] = True
    dl = np.zeros((2, E), bool)
    live = np.ones(2, bool)
    pre_vv = row.vv
    want = delta_ops.delta_extract(
        ingest_ops.ingest_rows(row, jnp.asarray(add), jnp.asarray(dl),
                               jnp.asarray(live)), pre_vv)
    assert bool(np.asarray(want.changed)[7])  # the uncovered lane ships
    for impl, fn in (("xla", ingest_ops.ingest_rows_delta),
                     ("pallas", pallas_ingest_rows_delta)):
        _, got, _ = fn(row, jnp.asarray(add), jnp.asarray(dl),
                       jnp.asarray(live), k_changed=16, k_deleted=16)
        _assert_trees_equal(got, want, impl)
