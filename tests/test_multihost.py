"""Multi-host layer (parallel/multihost.py): real 2-process coverage.

The DCN story (SURVEY §5.8) was previously untested — 81 LoC resting on
inspection.  These tests drive it two ways:

* unit tests for ``process_replica_block`` slicing/divisibility at
  ``process_count == 1`` (the in-process contract);
* a genuine 2-process ``jax.distributed`` run on CPU: a localhost
  coordinator, two worker processes each calling
  ``multihost.initialize`` + ``multihost.global_mesh``, running one
  sharded gossip round, and checking the digest agrees on both hosts.
  This is the same program shape a v5e-16 multi-host deployment runs,
  with DCN stood in by the local distributed service.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from go_crdt_playground_tpu.parallel import multihost  # noqa: E402


def test_process_replica_block_single_process():
    """At process_count == 1 the block is the whole replica axis."""
    assert multihost.process_replica_block(64) == (0, 64)


def test_process_replica_block_rejects_ragged_in_worker():
    """The divisibility guard needs process_count > 1 to be reachable —
    it is exercised inside the 2-process worker below (R=9 over 2
    processes raises instead of inventing an unrealizable placement)."""
    assert "process_replica_block(9)" in _WORKER


_WORKER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")

    from go_crdt_playground_tpu.parallel import multihost

    pid = int(sys.argv[1])
    multihost.initialize(coordinator_address=sys.argv[2],
                         num_processes=2, process_id=pid)
    assert jax.process_count() == 2
    assert jax.process_index() == pid
    # every process sees the GLOBAL device set
    devices = jax.devices()
    assert len(devices) == 2, devices
    mesh = multihost.global_mesh()

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding
    from go_crdt_playground_tpu.parallel import collectives, gossip
    from go_crdt_playground_tpu.parallel import mesh as mesh_mod
    from go_crdt_playground_tpu.models import awset

    R, E, A = 8, 16, 8
    lo, hi = multihost.process_replica_block(R)
    assert hi - lo == R // 2 and lo == pid * (R // 2)
    try:
        multihost.process_replica_block(9)
        raise SystemExit("expected ValueError for ragged replica axis")
    except ValueError:
        pass

    # host-local construction of the process's replica block, assembled
    # into one global sharded array per field
    specs = mesh_mod.partition_specs(awset.AWSetState)

    e = np.arange(E, dtype=np.uint32)[None, :]
    r = np.arange(lo, hi, dtype=np.uint32)[:, None]
    present = (e % (r % 3 + 2)) == 0
    counter = np.cumsum(present, axis=1, dtype=np.uint32) * present
    vv = np.zeros((hi - lo, A), np.uint32)
    vv[np.arange(hi - lo), np.arange(lo, hi)] = counter.max(axis=1)

    def globalize(specs, name, local, global_shape):
        sharding = NamedSharding(mesh, getattr(specs, name))
        return jax.make_array_from_process_local_data(
            sharding, local, global_shape)

    def build(state_cls, specs, **extra):
        fields = dict(
            vv=(vv, (R, A)),
            present=(present, (R, E)),
            dot_actor=(np.where(present, r, 0).astype(np.uint32), (R, E)),
            dot_counter=(counter, (R, E)),
            actor=(np.arange(lo, hi, dtype=np.uint32), (R,)),
            **extra,
        )
        return state_cls(**{{name: globalize(specs, name, local, shape)
                             for name, (local, shape) in fields.items()}})

    state = build(awset.AWSetState, specs)

    @jax.jit
    def step(s, perm):
        merged = gossip.gossip_round(s, perm, kernel="xla")
        return merged, collectives.converged(merged.present, merged.vv)

    out, conv = step(state, gossip.ring_perm(R, 1))
    jax.block_until_ready(out)
    # the digest is fully replicated: both hosts can read it
    print(f"WORKER_OK pid={{pid}} converged={{bool(conv)}}")

    # δ path over the same 2-process mesh: payload-compressed rounds +
    # collective GC frontier + digest, driven to convergence — the
    # v5e-16 multi-host program shape for the headline protocol
    from go_crdt_playground_tpu.models import awset_delta
    from go_crdt_playground_tpu.ops import delta as delta_ops

    zE = np.zeros((hi - lo, E), np.uint32)
    dstate = build(
        awset_delta.AWSetDeltaState,
        mesh_mod.partition_specs(awset_delta.AWSetDeltaState),
        deleted=(np.zeros((hi - lo, E), bool), (R, E)),
        del_dot_actor=(zE, (R, E)),
        del_dot_counter=(zE, (R, E)),
        processed=(vv, (R, A)),
    )

    @jax.jit
    def dstep(s, perm):
        s = gossip.delta_gossip_round(s, perm, delta_semantics="v2")
        frontier = delta_ops.gc_frontier(s.processed)
        s = delta_ops.gc_apply(s, frontier)
        return s, collectives.converged(s.present, s.vv)

    dconv = False
    for off in gossip.dissemination_offsets(R):
        dstate, dconv = dstep(dstate, gossip.ring_perm(R, off))
    jax.block_until_ready(dstate)
    print(f"WORKER_DELTA_OK pid={{pid}} converged={{bool(dconv)}}")
""").format(repo=REPO)


@pytest.mark.skipif(os.environ.get("CRDT_SKIP_DISTRIBUTED") == "1",
                    reason="distributed run disabled")
def test_two_process_distributed_gossip_round(tmp_path):
    """Two real OS processes, one jax.distributed service, one sharded
    gossip round over a DCN-spanning (2, 1) mesh."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # exactly one CPU device per process
    # scrub the TPU-tunnel plugin (same rationale as
    # __graft_entry__._scrubbed_cpu_env: it overrides JAX_PLATFORMS)
    if "PYTHONPATH" in env:
        kept = [p for p in env["PYTHONPATH"].split(os.pathsep)
                if p and ".axon_site" not in p.split(os.sep)]
        env["PYTHONPATH"] = os.pathsep.join(kept) if kept else ""
    for key in list(env):
        if key.startswith(("TPU_", "LIBTPU", "PJRT_", "AXON_",
                           "PALLAS_AXON")):
            env.pop(key)

    procs = [subprocess.Popen(
        [sys.executable, str(script), str(pid), coord],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True) for pid in (0, 1)]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        assert rc == 0, f"worker failed:\n{err[-3000:]}"
    assert "WORKER_OK pid=0" in outs[0][1]
    assert "WORKER_OK pid=1" in outs[1][1]
    # the δ fleet converged across the process boundary, and both hosts
    # read the same replicated digest
    assert "WORKER_DELTA_OK pid=0 converged=True" in outs[0][1]
    assert "WORKER_DELTA_OK pid=1 converged=True" in outs[1][1]
