"""The resilient anti-entropy runtime (net/antientropy.py).

Breaker transition table and failure classification as pure units (no
sockets, injected clock), then the SyncSupervisor against real Nodes on
localhost: typed errors from sync_with, retry/breaker metrics on the
Recorder, breaker recovery when a dead peer comes back, and the
checkpoint-restart path (a killed-and-restored replica reconverges via
the FULL-state first-contact branch)."""

import socket
import threading
import time

import numpy as np
import pytest

from go_crdt_playground_tpu.net import framing
from go_crdt_playground_tpu.net.antientropy import (CLOSED, HALF_OPEN, OPEN,
                                                    CircuitBreaker,
                                                    SyncSupervisor,
                                                    classify_failure)
from go_crdt_playground_tpu.net.peer import (ConnectFailed, Node,
                                             PeerProtocolError, PeerReset,
                                             PeerTimeout, SyncError)
from go_crdt_playground_tpu.obs import Recorder
from go_crdt_playground_tpu.utils.backoff import BackoffPolicy

E = 32
A = 4

FAST = BackoffPolicy(base_s=0.001, cap_s=0.005, max_retries=2, jitter=0.0)


# -- circuit breaker: the transition table, no wall clock ------------------


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def make_breaker(threshold=3, cooldown=10.0):
    clk = FakeClock()
    transitions = []
    br = CircuitBreaker(failure_threshold=threshold, cooldown_s=cooldown,
                        clock=clk,
                        on_transition=lambda o, n: transitions.append((o, n)))
    return br, clk, transitions


def test_breaker_closed_until_threshold():
    br, _, transitions = make_breaker(threshold=3)
    assert br.state == CLOSED
    for _ in range(2):
        br.record_failure()
        assert br.state == CLOSED and br.allow()
    br.record_failure()
    assert br.state == OPEN
    assert transitions == [(CLOSED, OPEN)]


def test_breaker_success_resets_consecutive_count():
    br, _, _ = make_breaker(threshold=2)
    br.record_failure()
    br.record_success()
    br.record_failure()
    assert br.state == CLOSED, "non-consecutive failures never open"


def test_breaker_open_blocks_until_cooldown_then_single_probe():
    br, clk, transitions = make_breaker(threshold=1, cooldown=10.0)
    br.record_failure()
    assert br.state == OPEN
    assert not br.allow()
    clk.t = 9.9
    assert not br.allow(), "cooldown not yet elapsed"
    clk.t = 10.0
    assert br.allow(), "cooldown elapsed -> half-open probe granted"
    assert br.state == HALF_OPEN
    assert not br.allow(), "exactly ONE probe per half-open window"
    assert transitions == [(CLOSED, OPEN), (OPEN, HALF_OPEN)]


def test_breaker_probe_success_closes():
    br, clk, transitions = make_breaker(threshold=1, cooldown=1.0)
    br.record_failure()
    clk.t = 1.0
    assert br.allow()
    br.record_success()
    assert br.state == CLOSED and br.allow()
    assert transitions[-1] == (HALF_OPEN, CLOSED)


def test_breaker_probe_failure_reopens_with_fresh_cooldown():
    br, clk, transitions = make_breaker(threshold=1, cooldown=5.0)
    br.record_failure()           # -> OPEN at t=0
    clk.t = 5.0
    assert br.allow()             # -> HALF_OPEN
    br.record_failure()           # probe failed -> OPEN, cooldown restarts
    assert br.state == OPEN
    clk.t = 9.9
    assert not br.allow(), "cooldown must be FRESH from the probe failure"
    clk.t = 10.0
    assert br.allow()
    assert transitions == [(CLOSED, OPEN), (OPEN, HALF_OPEN),
                           (HALF_OPEN, OPEN), (OPEN, HALF_OPEN)]


def test_breaker_trip_forces_open():
    br, clk, _ = make_breaker(threshold=5, cooldown=3.0)
    br.trip()
    assert br.state == OPEN and not br.allow()
    clk.t = 3.0
    assert br.allow() and br.state == HALF_OPEN


def test_breaker_validation():
    with pytest.raises(ValueError):
        CircuitBreaker(failure_threshold=0)
    with pytest.raises(ValueError):
        CircuitBreaker(cooldown_s=-1.0)


# -- failure classification -------------------------------------------------


def test_classification_table():
    cases = [
        (ConnectFailed("refused"), "connect_refused"),
        (PeerTimeout("slow dial", phase="connect"), "connect_timeout"),
        (PeerTimeout("slow hello", phase="hello"), "frame_deadline"),
        (PeerTimeout("slow payload", phase="payload"), "frame_deadline"),
        (PeerReset("torn"), "reset"),
        (PeerProtocolError("bad magic"), "protocol"),
        (framing.ProtocolError("bad magic"), "protocol"),
        (framing.TruncatedFrame("closed mid-frame"), "reset"),
        (framing.RemoteError("universe mismatch"), "remote"),
        (ConnectionResetError("reset by peer"), "reset"),
        (socket.timeout("raw"), "frame_deadline"),
        (OSError("raw dial failure"), "connect_refused"),
        (ValueError("not a sync failure"), "unknown"),
    ]
    for exc, expected in cases:
        assert classify_failure(exc) == expected, (exc, expected)


def test_typed_errors_keep_legacy_bases():
    """The compatibility contract: pre-hierarchy callers catch
    (OSError, framing.ProtocolError) — every typed error must land in
    one of those nets."""
    assert issubclass(ConnectFailed, OSError)
    assert issubclass(ConnectFailed, SyncError)
    assert issubclass(PeerTimeout, OSError)
    assert issubclass(PeerTimeout, socket.timeout)
    assert issubclass(PeerReset, OSError)
    assert issubclass(PeerProtocolError, framing.ProtocolError)


# -- typed errors out of the real sync_with --------------------------------


def test_sync_with_raises_connect_failed_on_dead_port():
    n = Node(0, E, A)
    # a port nothing listens on: bind-then-close reserves a dead one
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()
    with pytest.raises(ConnectFailed):
        n.sync_with(("127.0.0.1", port), timeout=2.0)


def test_sync_with_raises_peer_timeout_on_silent_server():
    # a server that accepts and never speaks: the HELLO reply deadline
    # must fire (phase attribution pinned), not the payload timeout
    silent = socket.create_server(("127.0.0.1", 0))
    try:
        n = Node(0, E, A)
        t0 = time.monotonic()
        with pytest.raises(PeerTimeout) as ei:
            n.sync_with(silent.getsockname()[:2], timeout=30.0,
                        hello_timeout_s=0.3)
        assert ei.value.phase == "hello"
        assert time.monotonic() - t0 < 5.0, \
            "the short HELLO deadline must undercut the payload timeout"
    finally:
        silent.close()


def test_sync_with_raises_peer_reset_on_abrupt_close():
    done = threading.Event()

    def accept_and_slam(srv):
        conn, _ = srv.accept()
        conn.close()
        done.set()

    srv = socket.create_server(("127.0.0.1", 0))
    threading.Thread(target=accept_and_slam, args=(srv,),
                     daemon=True).start()
    try:
        n = Node(0, E, A)
        with pytest.raises(PeerReset):
            n.sync_with(srv.getsockname()[:2], timeout=2.0)
        done.wait(2.0)
    finally:
        srv.close()


def test_sync_with_remote_error_propagates_unwrapped():
    a = Node(0, E, A)
    b = Node(1, E * 2, A)  # element-universe mismatch
    with b:
        addr = b.serve()
        with pytest.raises(framing.RemoteError, match="universe mismatch"):
            a.sync_with(addr)


# -- supervisor against real nodes -----------------------------------------


def test_supervisor_converges_and_counts():
    rec = Recorder()
    a = Node(0, E, A, recorder=rec)
    b = Node(1, E, A)
    c = Node(2, E, A)
    with b, c:
        addr_b, addr_c = b.serve(), c.serve()
        a.add(1)
        b.add(2)
        c.add(3)
        sup = SyncSupervisor(a, [addr_b, addr_c], policy=FAST,
                             interval_s=0.0, recorder=rec)
        summary = sup.sync_round()
        assert summary == {"succeeded": 2, "failed": 0, "skipped": 0}
        assert set(a.members()) == {1, 2, 3}
        counters = rec.snapshot()["counters"]
        assert counters["sync.successes"] == 2
        assert counters["sync.supervisor.rounds"] == 1


def test_supervisor_retries_then_opens_breaker_on_dead_peer():
    rec = Recorder()
    a = Node(0, E, A, recorder=rec)
    dead = ("127.0.0.1", 1)  # reserved port, nothing listens
    sup = SyncSupervisor(a, [dead], policy=FAST, breaker_threshold=2,
                         breaker_cooldown_s=30.0, interval_s=0.0,
                         recorder=rec)
    for _ in range(3):
        sup.sync_round()
    counters = rec.snapshot()["counters"]
    # per-failure-class retry counts: every failed attempt classified,
    # in-round retries counted separately
    assert counters["sync.failures.connect_refused"] >= 4
    assert counters["sync.retries.connect_refused"] >= 2
    assert counters["sync.peer_failures"] == 2
    assert counters["breaker.to_open"] == 1
    # third round found the breaker OPEN: skipped, no connect attempted
    assert counters["sync.skipped_open"] == 1
    assert sup.breaker(dead).state == OPEN
    # gauge mirrors the state (0=closed 1=open 2=half_open)
    assert rec.snapshot()["gauges"]["breaker.state.127.0.0.1:1"] == 1


def test_supervisor_breaker_recovers_when_peer_returns():
    rec = Recorder()
    a = Node(0, E, A, recorder=rec)
    a.add(5)
    b = Node(1, E, A)
    # reserve a port for b WITHOUT serving yet
    placeholder = socket.create_server(("127.0.0.1", 0))
    host, port = placeholder.getsockname()[:2]
    placeholder.close()
    sup = SyncSupervisor(a, [(host, port)], policy=FAST,
                         breaker_threshold=1, breaker_cooldown_s=0.05,
                         interval_s=0.0, recorder=rec)
    sup.sync_round()
    assert sup.breaker((host, port)).state == OPEN
    # peer comes up on that port; after the cooldown the half-open probe
    # must succeed and close the breaker
    with b:
        b.serve(host=host, port=port)
        deadline = time.monotonic() + 10.0
        while sup.breaker((host, port)).state != CLOSED:
            time.sleep(0.06)
            sup.sync_round()
            assert time.monotonic() < deadline, "breaker never recovered"
        assert 5 in b.members()
    counters = rec.snapshot()["counters"]
    assert counters["breaker.to_open"] >= 1
    assert counters["breaker.to_half_open"] >= 1
    assert counters["breaker.to_closed"] >= 1


def test_supervisor_trips_breaker_immediately_on_remote_error():
    rec = Recorder()
    a = Node(0, E, A, recorder=rec)
    b = Node(1, E * 2, A)  # incompatible universe: deterministic failure
    with b:
        addr = b.serve()
        sup = SyncSupervisor(a, [addr], policy=FAST, breaker_threshold=5,
                             interval_s=0.0, recorder=rec)
        sup.sync_round()
        # one shot, no retries, breaker OPEN despite threshold 5: the
        # peer REPORTED an incompatibility — hammering it cannot help
        counters = rec.snapshot()["counters"]
        assert counters["sync.failures.remote"] == 1
        assert "sync.retries.remote" not in counters
        assert sup.breaker(addr).state == OPEN


def test_supervisor_run_until_and_pacing_is_injected():
    rec = Recorder()
    a = Node(0, E, A, recorder=rec)
    b = Node(1, E, A)
    sleeps = []
    with b:
        addr = b.serve()
        b.add(7)
        sup = SyncSupervisor(a, [addr], policy=FAST, interval_s=0.5,
                             recorder=rec, sleep=sleeps.append)
        rounds = sup.run(max_rounds=3,
                         until=lambda: 7 in a.members())
        assert rounds == 1, "until() must stop the loop at convergence"
        assert not sleeps, "no pacing sleep after the final round"
        sup.run(max_rounds=2)
        assert len(sleeps) == 1 and 0.4 <= sleeps[0] <= 0.6, \
            "jittered cadence flows through the injected sleep"


def test_supervisor_background_thread_start_stop():
    a = Node(0, E, A)
    b = Node(1, E, A)
    with b:
        addr = b.serve()
        b.add(9)
        sup = SyncSupervisor(a, [addr], policy=FAST, interval_s=0.01)
        sup.start()
        with pytest.raises(RuntimeError):
            sup.start()
        deadline = time.monotonic() + 10.0
        while 9 not in a.members() and time.monotonic() < deadline:
            time.sleep(0.01)
        sup.stop()
        assert 9 in a.members()


# -- crash / recovery -------------------------------------------------------


def test_supervisor_periodic_checkpoint_and_restart_full_resync(tmp_path):
    """The crash-recovery story end to end: supervised checkpoints every
    N rounds; the node is killed; SyncSupervisor.restore brings it back
    from the checkpoint and the rejoined replica catches up through the
    FULL-state first-contact branch."""
    from go_crdt_playground_tpu.net.framing import MODE_FULL

    ck = str(tmp_path / "node0.ckpt")
    rec = Recorder()
    a = Node(0, E, A, recorder=rec)
    b = Node(1, E, A)
    with b:
        addr_b = b.serve()
        a.add(1, 2)
        sup = SyncSupervisor(a, [addr_b], policy=FAST, interval_s=0.0,
                             recorder=rec, checkpoint_path=ck,
                             checkpoint_every=2)
        sup.sync_round()
        sup.sync_round()   # round 2 -> checkpoint written
        assert rec.snapshot()["counters"]["sync.checkpoints"] == 1
        a.close()          # "kill" the node

        # the fleet moves on while node 0 is down
        b.add(3, 4)

        # restart from the checkpoint; node 0 rejoins and catches up
        rec2 = Recorder()
        sup2 = SyncSupervisor.restore(ck, [addr_b], recorder=rec2,
                                      policy=FAST, interval_s=0.0)
        restored = sup2.node
        assert restored.actor == 0
        assert set(restored.members()) == {1, 2}, "checkpoint state only"

        # a FRESH replica (actor 2) that never exchanged with node 0:
        # its first contact with the restored node must ride FULL state
        c = Node(2, E, A)
        with c:
            addr_c = c.serve()
            stats = restored.sync_with(addr_c)
            assert stats.mode_sent == MODE_FULL, \
                "restored replica's first contact ships FULL state"
        sup2.sync_round()
        assert set(restored.members()) >= {1, 2, 3, 4}, \
            "restored replica reconverged with the fleet"
