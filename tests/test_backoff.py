"""utils/backoff.py as a pure unit: no sockets, no sleeping, no wall
clock — the delay law (jitter bounds, monotone cap, determinism under a
fixed seed) is the contract both the anti-entropy supervisor and the
bridge client retry on."""

import pytest

from go_crdt_playground_tpu.utils.backoff import (Backoff, BackoffPolicy,
                                                  retry_call)


def test_policy_validation():
    with pytest.raises(ValueError, match="multiplier"):
        BackoffPolicy(multiplier=0.5)
    with pytest.raises(ValueError, match="jitter"):
        BackoffPolicy(jitter=1.0)
    with pytest.raises(ValueError, match="non-negative"):
        BackoffPolicy(base_s=-1.0)
    with pytest.raises(ValueError, match="max_retries"):
        BackoffPolicy(max_retries=-1)


def test_nominal_sequence_monotone_and_capped():
    p = BackoffPolicy(base_s=0.1, multiplier=2.0, cap_s=0.75,
                      max_retries=8, jitter=0.0)
    noms = [p.nominal(k) for k in range(8)]
    assert noms == sorted(noms), "nominal sequence must be monotone"
    assert noms[0] == 0.1
    assert all(n <= 0.75 for n in noms), "cap must bound every delay"
    assert noms[-1] == 0.75, "the cap is reached, not asymptotically missed"


def test_jitter_bounds():
    p = BackoffPolicy(base_s=0.1, multiplier=2.0, cap_s=10.0,
                      jitter=0.25, max_retries=6)
    for seed in range(50):
        for k, d in enumerate(p.delays(seed)):
            n = p.nominal(k)
            assert n * 0.75 <= d <= n * 1.25, (seed, k, d, n)


def test_zero_jitter_is_exact():
    p = BackoffPolicy(base_s=0.05, multiplier=3.0, cap_s=1.0,
                      jitter=0.0, max_retries=4)
    assert list(p.delays(0)) == pytest.approx([0.05, 0.15, 0.45, 1.0])


def test_deterministic_under_fixed_seed():
    p = BackoffPolicy(jitter=0.5, max_retries=10)
    assert list(p.delays(42)) == list(p.delays(42))
    assert list(p.delays(42)) != list(p.delays(43))


def test_backoff_cursor_budget_and_reset_replay():
    p = BackoffPolicy(base_s=0.01, max_retries=3, jitter=0.5)
    bo = Backoff(p, seed=7)
    first = [bo.next_delay() for _ in range(3)]
    assert all(d is not None for d in first)
    assert bo.next_delay() is None, "budget spent"
    bo.reset()
    assert [bo.next_delay() for _ in range(3)] == first, \
        "reset must replay the same jitter stream (whole-run determinism)"


def test_retry_call_succeeds_after_transient_failures():
    p = BackoffPolicy(base_s=0.01, max_retries=3, jitter=0.0)
    sleeps = []
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    assert retry_call(flaky, p, sleep=sleeps.append) == "ok"
    assert len(calls) == 3
    assert sleeps == [0.01, 0.02], "two failures -> two policy delays"


def test_retry_call_exhausts_budget_and_raises_last():
    p = BackoffPolicy(base_s=0.0, max_retries=2, jitter=0.0)
    calls = []

    def dead():
        calls.append(1)
        raise ConnectionRefusedError("down")

    with pytest.raises(ConnectionRefusedError):
        retry_call(dead, p, sleep=lambda _: None)
    assert len(calls) == 3, "1 attempt + max_retries retries"


def test_retry_call_does_not_absorb_unlisted_exceptions():
    p = BackoffPolicy(max_retries=5)
    calls = []

    def broken():
        calls.append(1)
        raise ValueError("a bug, not weather")

    with pytest.raises(ValueError):
        retry_call(broken, p, sleep=lambda _: None)
    assert len(calls) == 1, "non-retryable exceptions fail fast"
