"""δ-kernel conformance: the tensor δ path must match the spec AWSetDelta
bit-for-bit — entries, VVs, deletion log, processed vectors — in BOTH
semantics modes, on the reference's δ scenario and randomized soups.
GC (collective-frontier causal stability) is tested for safety and
convergence separately, since the spec tracks per-peer acks while the
batched SPMD design computes the exact global frontier.
"""

import random

import numpy as np
import pytest

from go_crdt_playground_tpu.models import awset_delta
from go_crdt_playground_tpu.models.spec import AWSetDelta, VersionVector
from go_crdt_playground_tpu.ops import delta as delta_ops
from go_crdt_playground_tpu.utils.codec import ElementDict, pack_awset_deltas


class DualWorldDelta:
    """Runs one op sequence on the spec δ model and the packed δ tensor
    path, asserting bitwise equality of all nine arrays after each step."""

    ARRAYS = ("vv", "present", "dot_actor", "dot_counter", "actor",
              "deleted", "del_dot_actor", "del_dot_counter", "processed")

    def __init__(self, num_replicas=2, num_elements=16, num_actors=None,
                 mode="reference", strict=True):
        A = num_actors if num_actors is not None else num_replicas
        self.A, self.E = A, num_elements
        self.mode, self.strict = mode, strict
        self.spec = [
            AWSetDelta(actor=i, version_vector=VersionVector([0] * A),
                       delta_semantics=mode,
                       strict_reference_semantics=strict)
            for i in range(num_replicas)
        ]
        self.state = awset_delta.init(num_replicas, num_elements, A)
        self.dictionary = ElementDict(capacity=num_elements)

    def add(self, r, *keys):
        self.spec[r].add(*keys)
        for k in keys:
            e = self.dictionary.encode(k)
            self.state = awset_delta.add_element(
                self.state, np.uint32(r), np.uint32(e))

    def del_(self, r, *keys):
        """One Del(k...) call — a single clock tick for the whole key set
        (awset-delta_test.go:15)."""
        self.spec[r].del_(*keys)
        sel = np.zeros(self.E, bool)
        for k in keys:
            sel[self.dictionary.encode(k)] = True
        self.state = awset_delta.del_elements(
            self.state, np.uint32(r), np.asarray(sel))

    def merge(self, dst, src):
        self.spec[dst].merge(self.spec[src])
        self.state = delta_ops.delta_merge_one_into(
            self.state, dst, self.state, src,
            delta_semantics=self.mode,
            strict_reference_semantics=self.strict)

    def check(self, context=""):
        packed = pack_awset_deltas(self.spec, self.dictionary, self.A)
        actual = awset_delta.to_arrays(self.state)
        for name in self.ARRAYS:
            assert np.array_equal(packed[name], actual[name]), (
                self.mode, context, name, packed[name], actual[name])

    def members(self, r):
        arr = awset_delta.to_arrays(self.state)
        return sorted(
            self.dictionary.decode(int(e))
            for e in np.nonzero(arr["present"][r])[0]
        )


@pytest.mark.parametrize("mode", ["reference", "v2"])
def test_delta_kernel_reference_scenario(mode):
    """TestAWSetDelta (awset-delta_test.go:168-189) on the tensor path."""
    w = DualWorldDelta(mode=mode)
    w.add(0, "A", "B"); w.add(1, "A", "C"); w.check()
    w.merge(0, 1); w.check("A<-B full")
    w.merge(1, 0); w.check("B<-A delta")
    assert w.members(0) == ["A", "B", "C"]
    w.del_(0, "B"); w.add(0, "D", "E"); w.add(1, "E"); w.check()
    w.merge(1, 0); w.check("B<-A delta 2")
    assert w.members(1) == ["A", "C", "D", "E"]
    w.merge(0, 1); w.check("A<-B delta (empty)")
    assert w.members(0) == ["A", "C", "D", "E"]


def test_delta_kernel_strict_clock_divergence():
    """The strict empty-δ VV-skip quirk must reproduce the exact divergent
    clocks of the reference replay (SURVEY §3.3: A=[5,2], B=[5,3])."""
    w = DualWorldDelta(mode="reference", strict=True)
    w.add(0, "A", "B"); w.add(1, "A", "C")
    w.merge(0, 1); w.merge(1, 0)
    w.del_(0, "B"); w.add(0, "D", "E"); w.add(1, "E")
    w.merge(1, 0); w.merge(0, 1); w.check("final")
    arr = awset_delta.to_arrays(w.state)
    assert arr["vv"][0].tolist() == [5, 2]
    assert arr["vv"][1].tolist() == [5, 3]


@pytest.mark.parametrize("mode,strict", [
    ("reference", True), ("reference", False), ("v2", True)])
@pytest.mark.parametrize("seed", [0, 1])
def test_delta_kernel_randomized_conformance(mode, strict, seed):
    """Randomized 3-replica op soups, bitwise agreement after every op in
    both semantics modes."""
    rng = random.Random(seed + (0 if mode == "reference" else 100)
                        + (0 if strict else 1000))
    universe = [f"k{i}" for i in range(10)]
    w = DualWorldDelta(num_replicas=3, num_elements=12, num_actors=3,
                       mode=mode, strict=strict)
    for step in range(100):
        p = rng.random()
        r = rng.randrange(3)
        if p < 0.4:
            w.add(r, rng.choice(universe))
        elif p < 0.65:
            # multi-key deletes exercise the shared-dot rule
            ks = rng.sample(universe, rng.randint(1, 2))
            w.del_(r, *ks)
        else:
            s = rng.randrange(3)
            if s != r:
                w.merge(r, s)
        w.check(f"mode={mode} seed={seed} step={step}")


def test_delta_payload_masks_match_spec_extraction():
    """delta_extract must produce exactly the (changed, deleted) key sets
    of MakeDeltaMergeData (awset-delta_test.go:79-105), including the
    re-add filter."""
    w = DualWorldDelta(mode="reference")
    w.add(0, "k", "q"); w.add(1, "z")
    w.merge(1, 0); w.merge(0, 1)
    w.del_(0, "k"); w.add(0, "k")   # deleted then re-added: record obsolete
    w.del_(0, "q")                  # genuinely deleted
    w.add(0, "new")
    changed_spec, deleted_spec = w.spec[0].make_delta_merge_data(
        w.spec[1].version_vector)
    import jax
    src = jax.tree.map(lambda x: x[0], w.state)
    dst_vv = w.state.vv[1]
    payload = delta_ops.delta_extract(src, dst_vv)
    changed_ids = {w.dictionary.decode(int(e))
                   for e in np.nonzero(np.asarray(payload.changed))[0]}
    deleted_ids = {w.dictionary.decode(int(e))
                   for e in np.nonzero(np.asarray(payload.deleted))[0]}
    assert changed_ids == set(changed_spec or {})
    assert deleted_ids == set(deleted_spec or {})


def test_gc_frontier_safety_and_convergence():
    """Collective-frontier GC: records drop exactly when every
    participating replica's processed vector covers them, and dropping
    them never breaks convergence."""
    w = DualWorldDelta(num_replicas=3, num_elements=12, num_actors=3,
                       mode="v2")
    w.add(0, "k"); w.add(1, "b"); w.add(2, "c")
    w.merge(1, 0); w.merge(2, 0); w.merge(0, 1); w.merge(0, 2)
    w.merge(1, 2); w.merge(2, 1)
    w.del_(0, "k")
    # Before anyone hears of the deletion, the frontier must not cover it.
    frontier = delta_ops.gc_frontier(w.state.processed)
    arr = awset_delta.to_arrays(w.state)
    e = w.dictionary.encode("k")
    assert arr["deleted"][0][e]
    del_counter = int(arr["del_dot_counter"][0][e])
    assert int(np.asarray(frontier)[0]) < del_counter
    gced = delta_ops.gc_apply(w.state, frontier)
    assert np.asarray(gced.deleted)[0][e], "record must survive"
    # Propagate to everyone, then the frontier covers it and GC drops it.
    w.merge(1, 0); w.merge(2, 0)
    frontier = delta_ops.gc_frontier(w.state.processed)
    assert int(np.asarray(frontier)[0]) >= del_counter
    gced = delta_ops.gc_apply(w.state, frontier)
    assert not np.asarray(gced.deleted).any()
    # State after GC still converges (no entries resurrect).
    for r in range(3):
        assert not np.asarray(gced.present)[r][e]


def test_gc_participation_mask_blocks_frontier():
    """A participating replica that has not processed the deletion blocks
    the frontier; excluding it via the mask unblocks (the operator's
    escape hatch for decommissioned replicas)."""
    w = DualWorldDelta(num_replicas=3, num_elements=8, num_actors=3,
                       mode="v2")
    w.add(0, "k"); w.add(1, "b"); w.add(2, "c")
    w.merge(1, 0); w.merge(2, 0); w.merge(0, 1); w.merge(0, 2)
    w.merge(1, 2); w.merge(2, 1)
    w.del_(0, "k")
    w.merge(1, 0)   # replica 2 never hears of it
    e = w.dictionary.encode("k")
    arr = awset_delta.to_arrays(w.state)
    del_counter = int(arr["del_dot_counter"][0][e])
    frontier = delta_ops.gc_frontier(w.state.processed)
    assert int(np.asarray(frontier)[0]) < del_counter
    masked = delta_ops.gc_frontier(
        w.state.processed, participating=np.array([True, True, False]))
    assert int(np.asarray(masked)[0]) >= del_counter


def test_add_elements_batch_matches_sequential_adds():
    """add_elements (one fused dispatch per Add(k...) call, the add-path
    analogue of the del_elements selector — VERDICT r1 #8) must be
    bitwise the per-key add_element loop, including the duplicate-key
    case where the loop's later tick overwrites the earlier dot."""
    def seed(st):
        # pre-existing foreign-actor dot with a high counter: the batched
        # overwrite must NOT keep it (Add overwrites unconditionally)
        return st._replace(
            present=st.present.at[0, 9].set(True),
            dot_actor=st.dot_actor.at[0, 9].set(1),
            dot_counter=st.dot_counter.at[0, 9].set(100),
        )

    for ids in ([3, 7, 1], [5], [2, 9, 2, 4, 2], list(range(12))):
        seq = seed(awset_delta.init(2, 16, 2))
        bat = seed(awset_delta.init(2, 16, 2))
        pad = seed(awset_delta.init(2, 16, 2))
        for e in ids:
            seq = awset_delta.add_element(seq, np.uint32(0), np.uint32(e))
        bat = awset_delta.add_elements(
            bat, np.uint32(0), np.asarray(ids, np.uint32))
        # the arity-bucketed form Node.add uses: zero-padded + count
        k = len(ids)
        bucket = 1 << (k - 1).bit_length()
        padded = np.zeros(bucket, np.uint32)
        padded[:k] = ids
        pad = awset_delta.add_elements(
            pad, np.uint32(0), padded, np.uint32(k))
        for name in DualWorldDelta.ARRAYS:
            a = np.asarray(getattr(seq, name))
            for variant, other in (("batch", bat), ("padded", pad)):
                b = np.asarray(getattr(other, name))
                assert np.array_equal(a, b), (ids, variant, name, a, b)


def test_v2_remove_arbitration_on_uncovered_sender_dots():
    """A sender whose VV does NOT cover its own shipped live dot — the
    compact-overflow state (ops/compact.py: partial data, NO clock
    advance) — ships a changed lane plus a matching deletion record.
    v2 removes only when the sender's CLOCK covers the live dot
    (models/spec.py arbitration), so the entry must SURVIVE; a
    'changed lanes are trivially covered' shortcut removes it (r4
    review repro).  Pinned on the XLA path and the fused kernel."""
    import jax.numpy as jnp

    from go_crdt_playground_tpu.ops import pallas_delta
    from go_crdt_playground_tpu.parallel import gossip

    R, E, A = 2, 8, 2
    zE = jnp.zeros((R, E), jnp.uint32)
    state = awset_delta.AWSetDeltaState(
        # row 0: receiver (actor 0) — saw the sender's counter 1 only
        # (delta dispatch engages, counter-2 dots are news), no entries
        # row 1: sender (actor 1) — live dot (1,2) AND deletion record
        # (1,2) on lane 0, with an all-zero VV (overflow state)
        vv=jnp.asarray([[0, 1], [0, 0]], jnp.uint32),
        present=jnp.zeros((R, E), bool).at[1, 0].set(True),
        dot_actor=zE.at[1, 0].set(1),
        dot_counter=zE.at[1, 0].set(2),
        actor=jnp.asarray([0, 1], jnp.uint32),
        deleted=jnp.zeros((R, E), bool).at[1, 0].set(True),
        del_dot_actor=zE.at[1, 0].set(1),
        del_dot_counter=zE.at[1, 0].set(2),
        processed=jnp.zeros((R, A), jnp.uint32),
    )
    perm = jnp.asarray([1, 0], jnp.uint32)
    want = gossip.delta_gossip_round(state, perm, delta_semantics="v2",
                                     kernel="xla")
    # the shipped entry survives: the sender's zero clock covers nothing
    assert bool(want.present[0, 0]), (
        "uncovered sender dot must not trigger removal")
    assert int(want.dot_counter[0, 0]) == 2
    got = pallas_delta.pallas_delta_gossip_round(state, perm,
                                                 delta_semantics="v2")
    for name in want._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(want, name)),
            np.asarray(getattr(got, name)), err_msg=name)
