"""Router HA (DESIGN.md §22): epoch fence adjudication, the standby
tail/promotion state machine, client failover, actuator re-resolution,
and the disk-full StorageDegraded path's WAL counter.

Everything here is IN-PROCESS and non-slow: real sockets on localhost,
tiny universes, the state machine driven through its ``poll_once``
seam — the subprocess/SIGKILL version is the slow-marked
``fleet_serve_soak.py --router-ha`` wrapper.
"""

import os
import socket
import threading

import pytest

from go_crdt_playground_tpu.serve import protocol
from go_crdt_playground_tpu.serve.client import AmbiguousOp, ServeClient
from go_crdt_playground_tpu.serve.frontend import ServeFrontend
from go_crdt_playground_tpu.shard.fleet import free_port
from go_crdt_playground_tpu.shard.ha import (POLL_FAILED, POLL_PROMOTED,
                                             POLL_TAILED, RouterStandby)
from go_crdt_playground_tpu.shard.handoff import (load_router_epoch,
                                                  persist_router_epoch)
from go_crdt_playground_tpu.shard.router import ShardRouter

E, A = 16, 2


def _addr(fe):
    return fe.addr


# ---------------------------------------------------------------------------
# wire + persistence plumbing
# ---------------------------------------------------------------------------


def test_ring_sync_codec_roundtrip():
    body = protocol.encode_ring_sync(7, 3, "router-a")
    assert protocol.decode_ring_sync(body) == (7, 3, "router-a")
    with pytest.raises(ValueError):
        protocol.encode_ring_sync(1, -1, "x")
    rec = {"router_epoch": 9, "generation": 2, "shards": {"s0": ["h", 1]}}
    rid, got = protocol.decode_ring_sync_reply(
        protocol.encode_ring_sync_reply(5, rec))
    assert rid == 5 and got == rec
    from go_crdt_playground_tpu.net.framing import ProtocolError
    with pytest.raises(ProtocolError):
        protocol.decode_ring_sync(body + b"\x00")
    with pytest.raises(ProtocolError):
        protocol.decode_ring_sync_reply(
            protocol.encode_ring_sync_reply(5, rec)[:3])


def test_router_epoch_file_roundtrip(tmp_path):
    d = str(tmp_path)
    assert load_router_epoch(d) == 0
    assert load_router_epoch(None) == 0
    persist_router_epoch(d, 4, "router-b")
    assert load_router_epoch(d) == 4
    # garbage reads as absent, never raises
    with open(os.path.join(d, "router_epoch.json"), "w") as f:
        f.write("{torn")
    assert load_router_epoch(d) == 0


def test_wal_append_errors_counter(tmp_path):
    """Satellite: an OSError in the WAL write path is counted at the
    site (wal.append_errors) and re-raised for the serving layer to
    classify typed."""
    from go_crdt_playground_tpu.obs import Recorder
    from go_crdt_playground_tpu.utils.wal import DeltaWal

    rec = Recorder()
    wal = DeltaWal(str(tmp_path / "wal"), fsync=False, recorder=rec)

    class _Enospc:
        def write(self, data):
            raise OSError(28, "No space left on device")

        def flush(self):
            pass

        def tell(self):
            return 0

        def close(self):
            pass

        def fileno(self):
            return -1

    with wal._lock:
        wal._file = _Enospc()
    with pytest.raises(OSError):
        wal.append(b"doomed")
    snap = rec.snapshot()["counters"]
    assert snap["wal.append_errors"] == 1
    assert "wal.appends" not in snap


# ---------------------------------------------------------------------------
# shard-side fence adjudication
# ---------------------------------------------------------------------------


def test_frontend_epoch_adjudication(tmp_path):
    """The shard half of the fence: adopt-and-persist higher epochs,
    reject stale claims typed, fence every admin verb for lower (or
    missing) announcements, stay dormant with no epoch ever seen."""
    fe = ServeFrontend(E, A, durable_dir=str(tmp_path / "n0"),
                       flush_ms=0.5)
    fe.serve()
    try:
        with ServeClient(_addr(fe)) as legacy:
            # fence dormant: an unannounced admin verb works (pre-HA)
            assert legacy.slice_pull([0, 1])
            # adopt epoch 5 (persisted), acked with the record
            with ServeClient(_addr(fe)) as c5:
                rec = c5.ring_sync(5, "router-a")
                assert rec["router_epoch"] == 5
                # a stale claim rejects typed
                with ServeClient(_addr(fe)) as c4:
                    with pytest.raises(protocol.StaleRouterEpoch):
                        c4.ring_sync(4, "router-old")
                    # ... and its admin verbs are fenced too
                    with pytest.raises(protocol.StaleRouterEpoch):
                        c4.slice_pull([0])
                # once a fence exists, a NEVER-announced connection is
                # fenced as well (a deposed pre-announce code path)
                with pytest.raises(protocol.StaleRouterEpoch):
                    legacy.slice_pull([0])
                with pytest.raises(protocol.StaleRouterEpoch):
                    legacy.frontier()
                with pytest.raises(protocol.StaleRouterEpoch):
                    import numpy as np

                    legacy.gc(np.zeros(A, np.uint32))
                # the announced-current connection keeps working
                assert c5.slice_pull([0, 1])
                # reads are NEVER fenced (serve-through-degradation)
                members, _vv = legacy.members()
                assert members == []
        assert load_router_epoch(str(tmp_path / "n0")) == 5
        snap = fe.recorder.snapshot()["counters"]
        assert snap["serve.router_epoch.adopted"] == 1
        assert snap["serve.rejects.stale_epoch"] >= 4
    finally:
        fe.close()


def test_frontend_epoch_survives_restart(tmp_path):
    """The fence is durable: a restarted shard still rejects the old
    epoch (a deposed primary cannot wait out a shard crash)."""
    d = str(tmp_path / "n0")
    fe = ServeFrontend(E, A, durable_dir=d, flush_ms=0.5)
    fe.serve()
    try:
        with ServeClient(_addr(fe)) as c:
            c.ring_sync(3, "router-b")
    finally:
        fe.close()
    fe2 = ServeFrontend(E, A, durable_dir=d, flush_ms=0.5)
    fe2.serve()
    try:
        with ServeClient(_addr(fe2)) as c:
            with pytest.raises(protocol.StaleRouterEpoch):
                c.ring_sync(2, "router-a")
            assert c.ring_sync(3, "router-b")["router_epoch"] == 3
    finally:
        fe2.close()


# ---------------------------------------------------------------------------
# router-side record + self-fence
# ---------------------------------------------------------------------------


def test_router_ring_record_and_self_fence(tmp_path):
    fe = ServeFrontend(E, A, flush_ms=0.5)
    fe.serve()
    router = ShardRouter({"s0": _addr(fe)}, E, seed=3,
                         state_dir=str(tmp_path / "router"),
                         router_epoch=1, router_id="router-a")
    addr = router.serve()
    try:
        with ServeClient(addr) as c:
            # the tail read: committed RouteState + epoch, addresses in
            rec = c.ring_sync(0, "standby")
            assert rec["router_epoch"] == 1
            assert rec["generation"] == 0
            assert rec["shards"] == {"s0": list(_addr(fe))}
            assert rec["elements"] == E and rec["seed"] == 3
            c.add(1)  # data plane serving normally
            # a higher claim arms the self-fence ...
            assert c.ring_sync(2, "router-b")["max_epoch_seen"] == 2
            assert router.deposed
            # ... RESHARD refuses typed with the reason
            ok, detail = c.reshard(protocol.RESHARD_LEAVE, "s0")
            assert not ok and "StaleRouterEpoch" in detail["reason"]
            # ... fleet GC refuses
            assert router.run_fleet_gc()["pushed"] is False
            # ... and the data plane sheds typed (stale-ring hazard)
            with pytest.raises(protocol.StaleRouterEpoch):
                c.add(2)
            # a STALE claim (below the max seen) rejects typed
            with ServeClient(addr) as c1:
                with pytest.raises(protocol.StaleRouterEpoch):
                    c1.ring_sync(1, "router-a-again")
            # reads keep serving through deposition
            members, _ = c.members()
            assert 1 in members
        snap = router.recorder.snapshot()["counters"]
        assert snap["router.shed.deposed"] >= 1
        assert snap["router.reshard.deposed"] == 1
    finally:
        router.close()
        fe.close()


# ---------------------------------------------------------------------------
# the standby state machine (poll_once seam — no wall-clock waits)
# ---------------------------------------------------------------------------


def test_standby_tail_promote_and_fence(tmp_path):
    fe = ServeFrontend(E, A, durable_dir=str(tmp_path / "s0"),
                       flush_ms=0.5)
    fe.serve()
    primary_state = str(tmp_path / "router-a")
    standby_state = str(tmp_path / "router-b")
    primary = ShardRouter({"s0": _addr(fe)}, E, seed=7,
                          state_dir=primary_state,
                          router_epoch=1, router_id="router-a")
    primary_addr = primary.serve()
    standby_port = free_port()
    standby = RouterStandby(
        primary_addr, {"s0": _addr(fe)}, E, seed=7,
        state_dir=standby_state, standby_id="router-b",
        listen_addr=("127.0.0.1", standby_port),
        failure_threshold=2)
    try:
        with ServeClient(primary_addr) as c:
            c.add(3)
        # tail: the committed ring lands in the standby's state_dir
        assert standby.poll_once() == POLL_TAILED
        rec = standby.last_record
        assert rec["router_epoch"] == 1 and rec["generation"] == 0
        from go_crdt_playground_tpu.shard.handoff import load_ring_file
        ring_rec = load_ring_file(standby_state)
        assert ring_rec["phase"] == "committed"
        assert ring_rec["shards"] == {"s0": list(_addr(fe))}
        # primary dies: below threshold first, then promote
        primary.close()
        assert standby.poll_once() == POLL_FAILED
        assert not standby.promoted
        assert standby.poll_once() == POLL_PROMOTED
        assert standby.promoted and standby.router is not None
        assert standby.promotion_s is not None
        promoted = standby.router
        # the promoted router adopted the TAILED committed ring under
        # the bumped persisted epoch
        assert promoted.router_epoch == 2
        assert load_router_epoch(standby_state) == 2
        assert promoted.route().generation == 0
        # the shard adjudicated the new epoch at promotion
        assert standby.announce_results == {"s0": True}
        assert load_router_epoch(str(tmp_path / "s0")) == 2
        # an HA client rides through: ordered list [dead primary,
        # standby] — reads rotate transparently, writes ack, and the
        # acked state from the old primary is still served
        with ServeClient([primary_addr,
                          ("127.0.0.1", standby_port)]) as hc:
            members, _ = hc.members()
            assert 3 in members
            hc.add(5)
            members, _ = hc.members()
            assert {3, 5} <= set(members)
            assert hc.active_addr == ("127.0.0.1", standby_port)
        # actuator re-resolution: the ordered list reads the promoted
        # router's ring state
        from go_crdt_playground_tpu.control.actuator import \
            ReshardActuator
        act = ReshardActuator(
            [primary_addr, ("127.0.0.1", standby_port)])
        gen, shards = act._ring_state()
        assert gen == 0 and shards == ("s0",)
    finally:
        standby.close()
        fe.close()


def test_standby_never_tailed_never_promotes(tmp_path):
    """The epoch-collision guard: a standby that has NEVER tailed the
    primary holds neither its committed ring nor its epoch — promoting
    would serve the flag ring under an epoch that can collide with the
    primary's own (equal epochs adjudicate as current: no fence).  It
    must keep polling instead, however many failures accumulate."""
    dead = ("127.0.0.1", free_port())  # nothing ever listened here
    standby = RouterStandby(dead, {"s0": ("127.0.0.1", 1)}, E,
                            state_dir=str(tmp_path / "b"),
                            failure_threshold=2, poll_timeout_s=0.5)
    try:
        for _ in range(5):
            assert standby.poll_once() == POLL_FAILED
        assert not standby.promoted and standby.router is None
        snap = standby.recorder.snapshot()["counters"]
        assert snap["router.ha.promote_blocked"] >= 3
        assert "router.ha.promotions" not in snap
    finally:
        standby.close()


def test_standby_does_not_promote_while_primary_healthy(tmp_path):
    fe = ServeFrontend(E, A, flush_ms=0.5)
    fe.serve()
    primary = ShardRouter({"s0": _addr(fe)}, E, seed=1,
                          router_epoch=1, router_id="router-a")
    primary_addr = primary.serve()
    standby = RouterStandby(primary_addr, {"s0": _addr(fe)}, E, seed=1,
                            state_dir=str(tmp_path / "b"),
                            failure_threshold=2)
    try:
        for _ in range(4):
            assert standby.poll_once() == POLL_TAILED
        assert not standby.promoted
        snap = standby.recorder.snapshot()["counters"]
        assert snap["router.ha.polls"] == 4
        assert "router.ha.promotions" not in snap
    finally:
        standby.close()
        primary.close()
        fe.close()


# ---------------------------------------------------------------------------
# client failover semantics
# ---------------------------------------------------------------------------


def test_client_ambiguous_inflight_and_rotation(tmp_path):
    """An op whose connection dies un-answered surfaces the TYPED
    AmbiguousOp (never silently resent); the next attempt rotates to
    the successor address and serves."""
    # addr0: a server that accepts, reads one frame, closes unanswered
    listener = socket.create_server(("127.0.0.1", 0))
    dead_addr = listener.getsockname()[:2]

    def one_shot():
        conn, _ = listener.accept()
        try:
            conn.recv(64)  # the op frame arrives ...
        finally:
            conn.close()   # ... and dies with no reply

    t = threading.Thread(target=one_shot, daemon=True)
    t.start()
    fe = ServeFrontend(E, A, flush_ms=0.5)
    fe.serve()
    try:
        c = ServeClient([dead_addr, _addr(fe)], timeout=10.0)
        try:
            with pytest.raises(AmbiguousOp):
                c.add(1)
            # the ledger's resubmit lands on the successor
            c.add(1)
            assert c.rotations >= 1
            assert c.active_addr == _addr(fe)
            members, _ = c.members()
            assert members == [1]
        finally:
            c.close()
    finally:
        fe.close()
        listener.close()
    # single-address clients keep the legacy fail-fast contract
    fe2 = ServeFrontend(E, A, flush_ms=0.5)
    fe2.serve()
    c2 = ServeClient(_addr(fe2))
    fe2.close()
    try:
        deadline = 50
        while not c2.closed and deadline:
            import time

            time.sleep(0.1)
            deadline -= 1
        assert c2.closed
        with pytest.raises(ConnectionError):
            c2.add(1)
    finally:
        c2.close()


def test_client_idempotent_reads_retry_across_list():
    """QUERY/STATS retry transparently on the successor when the
    active address refuses the dial entirely."""
    fe = ServeFrontend(E, A, flush_ms=0.5)
    fe.serve()
    dead = free_port()  # nothing listens here
    try:
        with ServeClient([("127.0.0.1", dead), _addr(fe)],
                         connect_timeout=1.0) as c:
            members, _ = c.members()
            assert members == []
            assert c.stats()["counters"] is not None
    finally:
        fe.close()
