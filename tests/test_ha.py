"""Router HA (DESIGN.md §22): epoch fence adjudication, the standby
tail/promotion state machine, client failover, actuator re-resolution,
and the disk-full StorageDegraded path's WAL counter.

Everything here is IN-PROCESS and non-slow: real sockets on localhost,
tiny universes, the state machine driven through its ``poll_once``
seam — the subprocess/SIGKILL version is the slow-marked
``fleet_serve_soak.py --router-ha`` wrapper.
"""

import os
import socket
import threading

import pytest

from go_crdt_playground_tpu.serve import protocol
from go_crdt_playground_tpu.serve.client import AmbiguousOp, ServeClient
from go_crdt_playground_tpu.serve.frontend import ServeFrontend
from go_crdt_playground_tpu.shard.fleet import free_port
from go_crdt_playground_tpu.shard.ha import (POLL_FAILED, POLL_PROMOTED,
                                             POLL_TAILED, RouterStandby)
from go_crdt_playground_tpu.shard.handoff import (load_router_epoch,
                                                  persist_router_epoch)
from go_crdt_playground_tpu.shard.router import ShardRouter

E, A = 16, 2


def _addr(fe):
    return fe.addr


# ---------------------------------------------------------------------------
# wire + persistence plumbing
# ---------------------------------------------------------------------------


def test_ring_sync_codec_roundtrip():
    body = protocol.encode_ring_sync(7, 3, "router-a")
    assert protocol.decode_ring_sync(body) == (7, 3, "router-a")
    with pytest.raises(ValueError):
        protocol.encode_ring_sync(1, -1, "x")
    rec = {"router_epoch": 9, "generation": 2, "shards": {"s0": ["h", 1]}}
    rid, got = protocol.decode_ring_sync_reply(
        protocol.encode_ring_sync_reply(5, rec))
    assert rid == 5 and got == rec
    from go_crdt_playground_tpu.net.framing import ProtocolError
    with pytest.raises(ProtocolError):
        protocol.decode_ring_sync(body + b"\x00")
    with pytest.raises(ProtocolError):
        protocol.decode_ring_sync_reply(
            protocol.encode_ring_sync_reply(5, rec)[:3])


def test_router_epoch_file_roundtrip(tmp_path):
    d = str(tmp_path)
    assert load_router_epoch(d) == 0
    assert load_router_epoch(None) == 0
    persist_router_epoch(d, 4, "router-b")
    assert load_router_epoch(d) == 4
    # garbage reads as absent, never raises
    with open(os.path.join(d, "router_epoch.json"), "w") as f:
        f.write("{torn")
    assert load_router_epoch(d) == 0


def test_wal_append_errors_counter(tmp_path):
    """Satellite: an OSError in the WAL write path is counted at the
    site (wal.append_errors) and re-raised for the serving layer to
    classify typed."""
    from go_crdt_playground_tpu.obs import Recorder
    from go_crdt_playground_tpu.utils.wal import DeltaWal

    rec = Recorder()
    wal = DeltaWal(str(tmp_path / "wal"), fsync=False, recorder=rec)

    class _Enospc:
        def write(self, data):
            raise OSError(28, "No space left on device")

        def flush(self):
            pass

        def tell(self):
            return 0

        def close(self):
            pass

        def fileno(self):
            return -1

    with wal._lock:
        wal._file = _Enospc()
    with pytest.raises(OSError):
        wal.append(b"doomed")
    snap = rec.snapshot()["counters"]
    assert snap["wal.append_errors"] == 1
    assert "wal.appends" not in snap


def test_wal_heals_torn_tail_before_probe_append(tmp_path):
    """A failed append can leave a PARTIAL record on disk; the degrade
    window's probe append must not land (and be acked) beyond it —
    recovery's prefix rule stops at the first tear, so everything acked
    after it would be silently dropped on restart.  append() heals the
    tail (truncate to the known-good end, reopen) before the next byte
    lands."""
    from go_crdt_playground_tpu.obs import Recorder
    from go_crdt_playground_tpu.utils.wal import DeltaWal

    rec = Recorder()
    path = str(tmp_path / "wal")
    wal = DeltaWal(path, fsync=False, recorder=rec)
    wal.append(b"acked-before")

    class _TornEnospc:
        """Writes HALF the record, then fails — the torn-mid-record
        shape a real ENOSPC/EIO leaves behind."""

        def __init__(self, f):
            self._f = f

        def write(self, data):
            self._f.write(data[:len(data) // 2])
            self._f.flush()
            raise OSError(28, "No space left on device")

        def __getattr__(self, name):
            return getattr(self._f, name)

    with wal._lock:
        wal._file = _TornEnospc(wal._file)
    with pytest.raises(OSError):
        wal.append(b"doomed-unacked")
    # the disk heals; the probe append repairs the tear FIRST, so its
    # record is readable — in-process and after a restart
    wal.append(b"acked-probe")
    assert list(wal.records()) == [b"acked-before", b"acked-probe"]
    snap = rec.snapshot()["counters"]
    assert snap["wal.tail_repairs"] == 1
    assert snap["wal.append_errors"] == 1
    wal.close()
    wal2 = DeltaWal(path, fsync=False)
    try:
        assert list(wal2.records()) == [b"acked-before", b"acked-probe"]
        # the in-process heal already trimmed the tear: open-time
        # repair found nothing left to do
        assert not wal2.torn_tail_repaired
    finally:
        wal2.close()


def test_wal_reopen_failure_stays_retryable_not_closed(tmp_path):
    """A transient OSError while opening the fresh segment (truncate's
    reset, a rotation) must leave the log retryable-degraded — the
    next append heals it, including the directory fsync for a segment
    that was never created — not wedged as 'closed' (a ValueError
    would escape the serving layer's typed OSError classification)."""
    from go_crdt_playground_tpu.obs import Recorder
    from go_crdt_playground_tpu.utils.wal import DeltaWal

    rec = Recorder()
    wal = DeltaWal(str(tmp_path / "wal"), fsync=False, recorder=rec)
    wal.append(b"pre-truncate")
    orig = wal._open_segment

    def flaky(seq, fresh):
        raise OSError(5, "Input/output error")

    wal._open_segment = flaky
    with pytest.raises(OSError):
        wal.truncate()
    wal._open_segment = orig
    wal.append(b"post-heal")  # heals: fresh segment, dir fsync'd
    assert list(wal.records()) == [b"post-heal"]
    snap = rec.snapshot()["counters"]
    assert snap["wal.tail_repairs"] == 1
    wal.close()


def test_wal_truncate_reclaims_despite_dirty_buffer(tmp_path):
    """truncate() IS the disk-space reclaim after a checkpoint: on a
    FULL disk the poisoned buffer's implicit flush re-raises ENOSPC at
    close — truncate must swallow that and still unlink (unlinking
    needs no free space, and every buffered byte is about to go)."""
    from go_crdt_playground_tpu.utils.wal import DeltaWal

    wal = DeltaWal(str(tmp_path / "wal"), fsync=False)
    wal.append(b"checkpointed")

    class _FullDisk:
        def write(self, data):
            raise OSError(28, "No space left on device")

        def close(self):
            raise OSError(28, "No space left on device")

        def __getattr__(self, name):
            raise AssertionError(f"unexpected {name} on full disk")

    with wal._lock:
        real, wal._file = wal._file, _FullDisk()
    real.close()
    with pytest.raises(OSError):
        wal.append(b"doomed")
    wal.truncate()  # reclaim proceeds past the re-raising close
    wal.append(b"post-reclaim")
    assert list(wal.records()) == [b"post-reclaim"]
    wal.close()


# ---------------------------------------------------------------------------
# shard-side fence adjudication
# ---------------------------------------------------------------------------


def test_frontend_epoch_adjudication(tmp_path):
    """The shard half of the fence: adopt-and-persist higher epochs,
    reject stale claims typed, fence every admin verb for lower (or
    missing) announcements, stay dormant with no epoch ever seen."""
    fe = ServeFrontend(E, A, durable_dir=str(tmp_path / "n0"),
                       flush_ms=0.5)
    fe.serve()
    try:
        with ServeClient(_addr(fe)) as legacy:
            # fence dormant: an unannounced admin verb works (pre-HA)
            assert legacy.slice_pull([0, 1])
            # adopt epoch 5 (persisted), acked with the record
            with ServeClient(_addr(fe)) as c5:
                rec = c5.ring_sync(5, "router-a")
                assert rec["router_epoch"] == 5
                # a stale claim rejects typed
                with ServeClient(_addr(fe)) as c4:
                    with pytest.raises(protocol.StaleRouterEpoch):
                        c4.ring_sync(4, "router-old")
                    # ... and its admin verbs are fenced too
                    with pytest.raises(protocol.StaleRouterEpoch):
                        c4.slice_pull([0])
                # once a fence exists, a NEVER-announced connection is
                # fenced as well (a deposed pre-announce code path)
                with pytest.raises(protocol.StaleRouterEpoch):
                    legacy.slice_pull([0])
                with pytest.raises(protocol.StaleRouterEpoch):
                    legacy.frontier()
                with pytest.raises(protocol.StaleRouterEpoch):
                    import numpy as np

                    legacy.gc(np.zeros(A, np.uint32))
                # the announced-current connection keeps working
                assert c5.slice_pull([0, 1])
                # reads are NEVER fenced (serve-through-degradation)
                members, _vv = legacy.members()
                assert members == []
        assert load_router_epoch(str(tmp_path / "n0")) == 5
        snap = fe.recorder.snapshot()["counters"]
        assert snap["serve.router_epoch.adopted"] == 1
        assert snap["serve.rejects.stale_epoch"] >= 4
    finally:
        fe.close()


def test_frontend_epoch_survives_restart(tmp_path):
    """The fence is durable: a restarted shard still rejects the old
    epoch (a deposed primary cannot wait out a shard crash)."""
    d = str(tmp_path / "n0")
    fe = ServeFrontend(E, A, durable_dir=d, flush_ms=0.5)
    fe.serve()
    try:
        with ServeClient(_addr(fe)) as c:
            c.ring_sync(3, "router-b")
    finally:
        fe.close()
    fe2 = ServeFrontend(E, A, durable_dir=d, flush_ms=0.5)
    fe2.serve()
    try:
        with ServeClient(_addr(fe2)) as c:
            with pytest.raises(protocol.StaleRouterEpoch):
                c.ring_sync(2, "router-a")
            assert c.ring_sync(3, "router-b")["router_epoch"] == 3
    finally:
        fe2.close()


# ---------------------------------------------------------------------------
# router-side record + self-fence
# ---------------------------------------------------------------------------


def test_router_ring_record_and_self_fence(tmp_path):
    fe = ServeFrontend(E, A, flush_ms=0.5)
    fe.serve()
    router = ShardRouter({"s0": _addr(fe)}, E, seed=3,
                         state_dir=str(tmp_path / "router"),
                         router_epoch=1, router_id="router-a")
    addr = router.serve()
    try:
        with ServeClient(addr) as c:
            # the tail read: committed RouteState + epoch, addresses in
            rec = c.ring_sync(0, "standby")
            assert rec["router_epoch"] == 1
            assert rec["generation"] == 0
            assert rec["shards"] == {"s0": list(_addr(fe))}
            assert rec["elements"] == E and rec["seed"] == 3
            c.add(1)  # data plane serving normally
            # a higher claim arms the self-fence ...
            assert c.ring_sync(2, "router-b")["max_epoch_seen"] == 2
            assert router.deposed
            # ... RESHARD refuses typed with the reason
            ok, detail = c.reshard(protocol.RESHARD_LEAVE, "s0")
            assert not ok and "StaleRouterEpoch" in detail["reason"]
            # ... fleet GC refuses
            assert router.run_fleet_gc()["pushed"] is False
            # ... and the data plane sheds typed (stale-ring hazard)
            with pytest.raises(protocol.StaleRouterEpoch):
                c.add(2)
            # a STALE claim (below the max seen) rejects typed
            with ServeClient(addr) as c1:
                with pytest.raises(protocol.StaleRouterEpoch):
                    c1.ring_sync(1, "router-a-again")
            # reads keep serving through deposition
            members, _ = c.members()
            assert 1 in members
        snap = router.recorder.snapshot()["counters"]
        assert snap["router.shed.deposed"] >= 1
        assert snap["router.reshard.deposed"] == 1
    finally:
        router.close()
        fe.close()


def test_epoch_zero_primary_restart_self_fences(tmp_path):
    """Resurrection containment without ``--router-epoch``: a primary
    left at the DEFAULT epoch 0 but given a state_dir still runs the
    serve()-time discovery probe (an epoch-0 RING_SYNC is a pure read),
    hears the promoted epoch from the shards, and starts life deposed —
    data plane sheds typed, reads keep serving."""
    shard_dir = str(tmp_path / "s0")
    fe = ServeFrontend(E, A, durable_dir=shard_dir, flush_ms=0.5)
    fe.serve()
    try:
        # a standby promoted to epoch 2 while this primary was dead
        with ServeClient(_addr(fe)) as c:
            c.ring_sync(2, "router-b")
        router = ShardRouter({"s0": _addr(fe)}, E,
                             state_dir=str(tmp_path / "router-a"),
                             router_id="router-a")  # epoch defaults to 0
        addr = router.serve()
        try:
            assert router.deposed
            with ServeClient(addr) as c:
                with pytest.raises(protocol.StaleRouterEpoch):
                    c.add(1)
                members, _ = c.members()  # reads serve through it
                assert members == []
            snap = router.recorder.snapshot()["counters"]
            assert snap["router.shed.deposed"] >= 1
        finally:
            router.close()
    finally:
        fe.close()


# ---------------------------------------------------------------------------
# the standby state machine (poll_once seam — no wall-clock waits)
# ---------------------------------------------------------------------------


def test_standby_tail_promote_and_fence(tmp_path):
    fe = ServeFrontend(E, A, durable_dir=str(tmp_path / "s0"),
                       flush_ms=0.5)
    fe.serve()
    primary_state = str(tmp_path / "router-a")
    standby_state = str(tmp_path / "router-b")
    primary = ShardRouter({"s0": _addr(fe)}, E, seed=7,
                          state_dir=primary_state,
                          router_epoch=1, router_id="router-a")
    primary_addr = primary.serve()
    standby_port = free_port()
    standby = RouterStandby(
        primary_addr, {"s0": _addr(fe)}, E, seed=7,
        state_dir=standby_state, standby_id="router-b",
        listen_addr=("127.0.0.1", standby_port),
        failure_threshold=2)
    try:
        with ServeClient(primary_addr) as c:
            c.add(3)
        # tail: the committed ring lands in the standby's state_dir
        assert standby.poll_once() == POLL_TAILED
        rec = standby.last_record
        assert rec["router_epoch"] == 1 and rec["generation"] == 0
        from go_crdt_playground_tpu.shard.handoff import load_ring_file
        ring_rec = load_ring_file(standby_state)
        assert ring_rec["phase"] == "committed"
        assert ring_rec["shards"] == {"s0": list(_addr(fe))}
        # primary dies: below threshold first, then promote
        primary.close()
        assert standby.poll_once() == POLL_FAILED
        assert not standby.promoted
        assert standby.poll_once() == POLL_PROMOTED
        assert standby.promoted and standby.router is not None
        assert standby.promotion_s is not None
        promoted = standby.router
        # the promoted router adopted the TAILED committed ring under
        # the bumped persisted epoch
        assert promoted.router_epoch == 2
        assert load_router_epoch(standby_state) == 2
        assert promoted.route().generation == 0
        # the shard adjudicated the new epoch at promotion
        assert standby.announce_results == {"s0": True}
        assert load_router_epoch(str(tmp_path / "s0")) == 2
        # an HA client rides through: ordered list [dead primary,
        # standby] — reads rotate transparently, writes ack, and the
        # acked state from the old primary is still served
        with ServeClient([primary_addr,
                          ("127.0.0.1", standby_port)]) as hc:
            members, _ = hc.members()
            assert 3 in members
            hc.add(5)
            members, _ = hc.members()
            assert {3, 5} <= set(members)
            assert hc.active_addr == ("127.0.0.1", standby_port)
        # actuator re-resolution: the ordered list reads the promoted
        # router's ring state
        from go_crdt_playground_tpu.control.actuator import \
            ReshardActuator
        act = ReshardActuator(
            [primary_addr, ("127.0.0.1", standby_port)])
        gen, shards = act._ring_state()
        assert gen == 0 and shards == ("s0",)
    finally:
        standby.close()
        fe.close()


def test_standby_never_tailed_never_promotes(tmp_path):
    """The epoch-collision guard: a standby that has NEVER tailed the
    primary holds neither its committed ring nor its epoch — promoting
    would serve the flag ring under an epoch that can collide with the
    primary's own (equal epochs adjudicate as current: no fence).  It
    must keep polling instead, however many failures accumulate."""
    dead = ("127.0.0.1", free_port())  # nothing ever listened here
    standby = RouterStandby(dead, {"s0": ("127.0.0.1", 1)}, E,
                            state_dir=str(tmp_path / "b"),
                            failure_threshold=2, poll_timeout_s=0.5)
    try:
        for _ in range(5):
            assert standby.poll_once() == POLL_FAILED
        assert not standby.promoted and standby.router is None
        snap = standby.recorder.snapshot()["counters"]
        assert snap["router.ha.promote_blocked"] >= 3
        assert "router.ha.promotions" not in snap
    finally:
        standby.close()


def test_standby_does_not_promote_while_primary_healthy(tmp_path):
    fe = ServeFrontend(E, A, flush_ms=0.5)
    fe.serve()
    primary = ShardRouter({"s0": _addr(fe)}, E, seed=1,
                          router_epoch=1, router_id="router-a")
    primary_addr = primary.serve()
    standby = RouterStandby(primary_addr, {"s0": _addr(fe)}, E, seed=1,
                            state_dir=str(tmp_path / "b"),
                            failure_threshold=2)
    try:
        for _ in range(4):
            assert standby.poll_once() == POLL_TAILED
        assert not standby.promoted
        snap = standby.recorder.snapshot()["counters"]
        assert snap["router.ha.polls"] == 4
        assert "router.ha.promotions" not in snap
    finally:
        standby.close()
        primary.close()
        fe.close()


def test_promote_is_single_entry(tmp_path):
    """A manual promote() racing the poll loop (or a second retry) must
    never build TWO routers: with listen_addr=None (embedded use) both
    would survive and one would leak its shard links and reader
    threads.  The promotion lock serializes the whole sequence; the
    loser returns the winner's router."""
    fe = ServeFrontend(E, A, durable_dir=str(tmp_path / "s0"),
                       flush_ms=0.5)
    fe.serve()
    standby = RouterStandby(("127.0.0.1", free_port()),
                            {"s0": _addr(fe)}, E,
                            state_dir=str(tmp_path / "b"),
                            standby_id="router-b")
    routers = []
    barrier = threading.Barrier(2)

    def race():
        barrier.wait()
        routers.append(standby.promote(reason="race"))

    try:
        threads = [threading.Thread(target=race) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30.0)
        assert len(routers) == 2
        assert routers[0] is routers[1]
        assert standby.router is routers[0]
        snap = standby.recorder.snapshot()["counters"]
        assert snap["router.ha.promotions"] == 1
    finally:
        standby.close()
        fe.close()


def test_standby_warns_on_epoch_zero_primary(tmp_path):
    """The fence is only airtight when a resurrected primary can
    rediscover the adjudicated epoch: tailing a primary that runs the
    default epoch 0 (and so may restart blind) is loud — one warning
    per standby plus a counter — never fatal."""
    fe = ServeFrontend(E, A, flush_ms=0.5)
    fe.serve()
    primary = ShardRouter({"s0": _addr(fe)}, E)  # pre-HA default: 0
    primary_addr = primary.serve()
    standby = RouterStandby(primary_addr, {"s0": _addr(fe)}, E,
                            state_dir=str(tmp_path / "b"))
    try:
        with pytest.warns(RuntimeWarning, match="router epoch 0"):
            assert standby.poll_once() == POLL_TAILED
        assert standby.poll_once() == POLL_TAILED  # warned once only
        snap = standby.recorder.snapshot()["counters"]
        assert snap["router.ha.primary_epoch_zero"] == 1
    finally:
        standby.close()
        primary.close()
        fe.close()


# ---------------------------------------------------------------------------
# client failover semantics
# ---------------------------------------------------------------------------


def test_client_ambiguous_inflight_and_rotation(tmp_path):
    """An op whose connection dies un-answered surfaces the TYPED
    AmbiguousOp (never silently resent); the next attempt rotates to
    the successor address and serves."""
    # addr0: a server that accepts, reads one frame, closes unanswered
    listener = socket.create_server(("127.0.0.1", 0))
    dead_addr = listener.getsockname()[:2]

    def one_shot():
        conn, _ = listener.accept()
        try:
            conn.recv(64)  # the op frame arrives ...
        finally:
            conn.close()   # ... and dies with no reply

    t = threading.Thread(target=one_shot, daemon=True)
    t.start()
    fe = ServeFrontend(E, A, flush_ms=0.5)
    fe.serve()
    try:
        c = ServeClient([dead_addr, _addr(fe)], timeout=10.0)
        try:
            with pytest.raises(AmbiguousOp):
                c.add(1)
            # the ledger's resubmit lands on the successor
            c.add(1)
            assert c.rotations >= 1
            assert c.active_addr == _addr(fe)
            members, _ = c.members()
            assert members == [1]
        finally:
            c.close()
    finally:
        fe.close()
        listener.close()
    # single-address clients keep the legacy fail-fast contract
    fe2 = ServeFrontend(E, A, flush_ms=0.5)
    fe2.serve()
    c2 = ServeClient(_addr(fe2))
    fe2.close()
    try:
        deadline = 50
        while not c2.closed and deadline:
            import time

            time.sleep(0.1)
            deadline -= 1
        assert c2.closed
        with pytest.raises(ConnectionError):
            c2.add(1)
    finally:
        c2.close()


def test_client_idempotent_reads_retry_across_list():
    """QUERY/STATS retry transparently on the successor when the
    active address refuses the dial entirely."""
    fe = ServeFrontend(E, A, flush_ms=0.5)
    fe.serve()
    dead = free_port()  # nothing listens here
    try:
        with ServeClient([("127.0.0.1", dead), _addr(fe)],
                         connect_timeout=1.0) as c:
            members, _ = c.members()
            assert members == []
            assert c.stats()["counters"] is not None
    finally:
        fe.close()


def test_stale_epoch_reject_only_rotates_its_own_connection():
    """A StaleRouterEpoch reject tears down the connection it ARRIVED
    on — never a newer socket a concurrent failover re-dial already
    replaced it with (shutting that down would kill a healthy
    connection and surface spurious AmbiguousOp for its in-flight
    ops)."""
    import time as time_mod

    from go_crdt_playground_tpu.serve.client import PendingOp

    class _FakeSock:
        def __init__(self):
            self.shut = False

        def shutdown(self, how):
            self.shut = True

    fe = ServeFrontend(E, A, flush_ms=0.5)
    fe.serve()
    try:
        with ServeClient([_addr(fe), ("127.0.0.1", free_port())]) as c:
            with c._lock:
                cur_gen = c._gen
                c._pending[9901] = PendingOp(9901, time_mod.monotonic())
                dial_before = c._next_dial
            # a reject from a SUPERSEDED connection: no rotation, and
            # the (stale) socket it came on is left alone too — its
            # reader's death sweep already owns that teardown
            stale_sock = _FakeSock()
            c._finish(9901, protocol.StaleRouterEpoch("deposed"),
                      time_mod.monotonic(), stale_sock, cur_gen - 1)
            assert not stale_sock.shut
            with c._lock:
                assert c._next_dial == dial_before
            members, _ = c.members()  # the live connection still serves
            assert members == []
            # the same reject on the CURRENT connection rotates it
            with c._lock:
                cur_gen = c._gen
                c._pending[9902] = PendingOp(9902, time_mod.monotonic())
            live_sock = _FakeSock()
            c._finish(9902, protocol.StaleRouterEpoch("deposed"),
                      time_mod.monotonic(), live_sock, cur_gen)
            assert live_sock.shut
            with c._lock:
                assert c._next_dial != dial_before
    finally:
        fe.close()
