"""SLO-aware background compaction (serve/compaction.py): headroom
decision, exponential backoff, deletion-record GC against the provable
frontier, WAL-driven checkpoint rotation — all pinned deterministically
through the ``run_cycle`` seam (no thread timing), plus one end-to-end
frontend integration."""

import os
import time

import numpy as np
import pytest

from go_crdt_playground_tpu.net.peer import Node
from go_crdt_playground_tpu.obs import Recorder
from go_crdt_playground_tpu.obs.metrics import percentile_of_counts
from go_crdt_playground_tpu.serve.compaction import CompactionScheduler

E, A = 48, 3


def _node(rec, **kw):
    return Node(0, E, A, recorder=rec, **kw)


def test_percentile_of_counts_windows():
    rec = Recorder()
    for v in (0.001,) * 90 + (2.0,) * 10:
        rec.observe("lat", v)
    h1 = rec.histogram("lat")
    assert percentile_of_counts(h1, 0.50) == pytest.approx(0.001, rel=0.5)
    assert percentile_of_counts(h1, 0.99) >= 1.0
    assert percentile_of_counts([0] * len(h1), 0.99) is None  # empty window
    # a window diff isolates RECENT behavior from cumulative history
    for v in (0.5,) * 10:
        rec.observe("lat", v)
    h2 = rec.histogram("lat")
    window = [a - b for a, b in zip(h2, h1)]
    assert percentile_of_counts(window, 0.5) == pytest.approx(0.5, rel=0.5)


def test_no_headroom_backs_off_exponentially():
    rec = Recorder()
    sched = CompactionScheduler(_node(rec), rec, interval_s=0.5,
                                queue_depth_max=4, max_backoff_s=3.0)
    rec.set_gauge("serve.queue.depth", 50)  # saturated
    waits = []
    for _ in range(4):
        out = sched.run_cycle()
        assert out["ran"] is False
        waits.append(out["backoff_s"])
    assert waits == [1.0, 2.0, 3.0, 3.0]  # doubles, then caps
    snap = rec.snapshot()
    assert snap["counters"]["compact.backoffs"] == 4
    assert snap["gauges"]["compact.headroom"] == 0.0
    # headroom returns -> the wait resets to the base interval
    rec.set_gauge("serve.queue.depth", 0)
    out = sched.run_cycle()
    assert out["ran"] is True
    assert sched._wait_s == 0.5


def test_recent_latency_spike_blocks_compaction():
    """The windowed p99 gates the cycle: an old idle history must NOT
    mask a current spike, and an old spike must not block forever."""
    rec = Recorder()
    sched = CompactionScheduler(_node(rec), rec, interval_s=0.5,
                                p99_budget_s=0.05)
    rec.set_gauge("serve.queue.depth", 0)
    for _ in range(50):
        rec.observe("serve.ingest_latency_s", 0.001)
    assert sched.run_cycle()["ran"] is True  # first window: calm
    for _ in range(20):
        rec.observe("serve.ingest_latency_s", 0.5)  # spike NOW
    assert sched.run_cycle()["ran"] is False
    assert sched.run_cycle()["ran"] is True  # spike aged out of window


def test_gc_drops_stable_deletions_and_reports_occupancy(tmp_path):
    rec = Recorder()
    node = _node(rec)
    node.add(*range(10))
    node.delete(1, 2, 3)
    # membership is DECLARED: without a declaration GC is disabled
    # (an undeclared frontier is all-zeros — restart-safe, unlike any
    # "have I heard a peer?" heuristic)
    undeclared = CompactionScheduler(node, rec, interval_s=0.5)
    rec.set_gauge("serve.queue.depth", 0)
    out = undeclared.run_cycle()
    assert out["ran"] is True and out["gc"] is None
    # the explicit isolated declaration (participants=()): this
    # replica IS the deployment, its own processed vector is the
    # frontier, every deletion record is provably stable
    sched = CompactionScheduler(node, rec, interval_s=0.5,
                                gc_participants=())
    out = sched.run_cycle()
    assert out["gc"] == {"dropped": 3, "remaining": 0}
    snap = rec.snapshot()
    assert snap["counters"]["compact.gc_runs"] == 1
    assert snap["counters"]["compact.gc_dropped_lanes"] == 3
    assert snap["gauges"]["compact.deleted_lanes"] == 0
    assert sorted(int(e) for e in node.members()) == [0] + list(range(4, 10))


def test_gc_frontier_waits_for_peer_acknowledgement():
    """Mid-fleet, a deletion record survives until every DECLARED
    participant's advertised ``processed`` vector covers it — the
    provable half of causal stability (ops/delta.gc_frontier,
    per-participant) — and an UNCONFIGURED frontier disables GC
    entirely once any peer has been heard (gossip is transitive:
    membership cannot be guessed from traffic)."""
    rec = Recorder()
    node = _node(rec)
    node.add(1, 2)
    node.delete(1)
    # a peer that has NOT processed our deletes yet advertises zeros
    import jax

    peer = Node(1, E, A)
    prow = jax.tree.map(lambda x: x[0], peer._state)
    from go_crdt_playground_tpu.net import framing as fr
    from go_crdt_playground_tpu.ops import delta as delta_ops

    payload = delta_ops.delta_extract(prow, np.zeros(A, np.uint32))
    node.apply_payload_body(fr.encode_payload_msg(
        fr.MODE_DELTA, 1, np.asarray(prow.processed), payload))
    assert node.gc_deletions(
        participants=[1])["dropped"] == 0  # peer hasn't caught up
    # the peer now advertises a processed vector covering our clock
    caught_up = np.asarray([10, 10, 10], np.uint32)
    payload = delta_ops.delta_extract(prow, np.zeros(A, np.uint32))
    node.apply_payload_body(fr.encode_payload_msg(
        fr.MODE_DELTA, 1, caught_up, payload))
    # no participant set: a node that has heard ANY peer refuses to GC
    # (a never-heard replica may hold our elements via transitive
    # gossip and would keep them forever past a dropped record)
    assert node.gc_deletions()["dropped"] == 0
    assert np.all(node.deletion_frontier() == 0)
    # an undeclared/unheard participant blocks GC too
    assert node.gc_deletions(participants=[1, 2])["dropped"] == 0
    # the declared set caught up: the record is provably stable
    assert node.gc_deletions(participants=[1])["dropped"] == 1


def test_gc_skipped_mid_heal_and_on_reference_semantics():
    rec = Recorder()
    node = _node(rec)
    node.add(1)
    node.delete(1)
    with node._lock:
        node.full_resync_pending = True
    sched = CompactionScheduler(node, rec, interval_s=0.5,
                                gc_participants=())
    rec.set_gauge("serve.queue.depth", 0)
    out = sched.run_cycle()
    assert out["ran"] is True and out["gc"] is None  # healing: no GC
    with node._lock:
        node.full_resync_pending = False
    assert sched.run_cycle()["gc"] is not None  # heal done: GC resumes
    ref = Node(0, E, A, delta_semantics="reference")
    with pytest.raises(ValueError, match="v2"):
        ref.gc_deletions()


def test_checkpoint_rotation_waits_for_wal_growth(tmp_path):
    from go_crdt_playground_tpu.utils.checkpoint import CheckpointStore
    from go_crdt_playground_tpu.utils.wal import DeltaWal

    d = str(tmp_path / "durable")
    rec = Recorder()
    node = _node(rec)
    node.wal = DeltaWal(os.path.join(d, "wal"), recorder=rec,
                        fsync=False)
    store = CheckpointStore(d, recorder=rec)
    calls = []

    def ckpt():
        calls.append(node.save_durable(store))

    sched = CompactionScheduler(node, rec, checkpoint=ckpt,
                                interval_s=0.5,
                                checkpoint_wal_bytes=200)
    sched._ckpt_base_bytes = rec.counter("wal.appended_bytes")
    rec.set_gauge("serve.queue.depth", 0)
    node.add(1)
    assert sched.run_cycle()["checkpointed"] is False  # not enough WAL
    for i in range(2, 30):
        node.add(i)
    out = sched.run_cycle()
    assert out["checkpointed"] is True
    assert calls == [1]
    assert rec.snapshot()["counters"]["compact.checkpoints"] == 1
    # rotation retired the sealed segments: replay-from-birth shrank
    assert node.wal.record_count() == 0
    # and does not re-checkpoint until the WAL grows again
    assert sched.run_cycle()["checkpointed"] is False
    with node._lock:
        node.wal.close()


def test_fleet_gc_protocol_roundtrip():
    """The FRONTIER/GC wire codecs: self-describing, trailing-byte
    strict, flags preserved."""
    from go_crdt_playground_tpu.serve import protocol

    fr = np.asarray([5, 0, 9], np.uint32)
    proc = np.asarray([7, 1, 9], np.uint32)
    body = protocol.encode_frontier_reply(3, fr, proc, True)
    rid, f2, p2, iso = protocol.decode_frontier_reply(body)
    assert (rid, iso) == (3, True)
    assert np.array_equal(f2, fr) and np.array_equal(p2, proc)
    body = protocol.encode_frontier_reply(4, fr, proc, False)
    assert protocol.decode_frontier_reply(body)[3] is False
    rid, f3 = protocol.decode_gc(protocol.encode_gc(9, fr))
    assert rid == 9 and np.array_equal(f3, fr)
    assert protocol.decode_gc_reply(
        protocol.encode_gc_reply(1, 2, 3)) == (1, 2, 3)
    with pytest.raises(Exception):
        protocol.decode_gc(protocol.encode_gc(9, fr) + b"x")


def test_fleet_gc_router_aggregates_true_minimum(tmp_path):
    """ROADMAP item c pin: the router aggregates per-shard
    ``deletion_frontier()``s into the TRUE fleet minimum.

    Three phases against a 2-shard in-process fleet (isolated GC
    declarations — no anti-entropy peers):

    1. static fleet: a shard that provably holds NO lane-a state
       (isolated + zero applied vv for the lane) is no constraint on
       lane a, so fleet GC drops exactly what per-shard isolated GC
       would — the lane mask that keeps disjoint keyspaces from
       pinning every foreign lane to zero forever;
    2. cross-shard state: once s1 holds actor-0-dotted state at an OLD
       clock (a moved slice / relayed payload), s0's newer deletion
       records must SURVIVE fleet GC — even though s0's own isolated
       evidence covers them (the per-node-evidence wrongness this
       subsystem exists to prevent);
    3. s1 catches up past the record clocks: the fleet minimum now
       covers them and the records drop.

    Plus: an unreachable shard blocks the whole round (unknown
    evidence must read as zero everywhere)."""
    import jax

    from go_crdt_playground_tpu.net import framing as fr
    from go_crdt_playground_tpu.ops import delta as delta_ops
    from go_crdt_playground_tpu.serve.client import ServeClient
    from go_crdt_playground_tpu.serve.frontend import ServeFrontend
    from go_crdt_playground_tpu.shard.router import ShardRouter

    fes = [ServeFrontend(E, A, actor=i, durable_dir=str(tmp_path / f"s{i}"),
                         max_batch=8, flush_ms=1.0)
           for i in range(2)]
    addrs = {f"s{i}": fe.serve() for i, fe in enumerate(fes)}
    router = ShardRouter(addrs, E, seed=5)
    addr = router.serve()
    try:
        owned0 = [e for e in range(E)
                  if router.ring.shards[router._owner[e]] == "s0"]
        assert len(owned0) >= 4
        with ServeClient(addr) as c:
            c.add(*owned0[:4])
            c.delete(owned0[0], owned0[1])
            # phase 1: s1 has zero lane-0 vv -> excluded from lane 0's
            # min -> fleet GC == isolated GC for s0's records
            out = router.run_fleet_gc()
            assert out["pushed"] is True and out["dropped"] == 2

            # phase 2: s1 applies an actor-0 payload at clock 1 (the
            # stale cross-shard copy); s0 deletes at a NEWER clock
            scratch = Node(0, E, A)
            scratch.add(owned0[2])
            srow = jax.tree.map(lambda x: x[0], scratch._state)
            payload = delta_ops.delta_extract(
                srow, np.zeros(A, np.uint32))
            fes[1].node.apply_payload_body(fr.encode_payload_msg(
                fr.MODE_DELTA, 0, np.asarray(srow.processed), payload))
            assert int(np.asarray(
                fes[1].node._state.processed[0])[0]) == 1
            c.delete(owned0[2], owned0[3])
            out = router.run_fleet_gc()
            assert out["pushed"] is True and out["dropped"] == 0
            # ... while s0's OWN isolated evidence covers the records
            # (per-shard GC would have dropped them wrongly)
            assert fes[0].node.deletion_frontier(())[0] > 1
            with fes[0].node._lock:
                assert int(np.asarray(
                    fes[0].node._state.deleted[0]).sum()) == 2

            # phase 3: s1 catches up past the record clocks
            while int(np.asarray(scratch._state.processed[0])[0]) < 32:
                scratch.add(owned0[2])
                scratch.delete(owned0[2])
            srow = jax.tree.map(lambda x: x[0], scratch._state)
            payload = delta_ops.delta_extract(
                srow, np.zeros(A, np.uint32))
            fes[1].node.apply_payload_body(fr.encode_payload_msg(
                fr.MODE_DELTA, 0, np.asarray(srow.processed), payload))
            out = router.run_fleet_gc()
            assert out["pushed"] is True and out["dropped"] == 2
            with fes[0].node._lock:
                assert int(np.asarray(
                    fes[0].node._state.deleted[0]).sum()) == 0

        # an unreachable shard's evidence is unknown: no round
        fes[1].close()
        out = router.run_fleet_gc()
        assert out["pushed"] is False and "unreachable" in out["reason"]
        snap = router.recorder.snapshot()
        assert snap["counters"]["router.fleet_gc.partial"] == 1
        assert snap["counters"]["router.fleet_gc.runs"] == 3
    finally:
        router.close()
        for fe in fes:
            fe.close()


def test_frontend_integration_compacts_under_idle(tmp_path):
    """End to end: a frontend with compaction enabled GCs deletion
    lanes while idle and keeps serving; the counters surface in the
    STATS dialect like every other SLO number."""
    from go_crdt_playground_tpu.serve.client import ServeClient
    from go_crdt_playground_tpu.serve.frontend import ServeFrontend

    fe = ServeFrontend(E, A, durable_dir=str(tmp_path / "n0"),
                       max_batch=8, flush_ms=1.0,
                       compact_interval_s=0.05)
    fe.serve()
    try:
        with ServeClient(fe.addr) as c:
            c.add(1, 2, 3)
            c.delete(2)
            deadline = time.monotonic() + 30.0
            dropped = 0
            while time.monotonic() < deadline:
                snap = c.stats()
                dropped = snap["counters"].get(
                    "compact.gc_dropped_lanes", 0)
                if dropped:
                    break
                time.sleep(0.05)
            assert dropped == 1, "idle frontend never GC'd the deletion"
            members, _ = c.members()
            assert members == [1, 3]
            c.add(10)  # still serving after maintenance
            members, _ = c.members()
            assert members == [1, 3, 10]
    finally:
        fe.close()
