"""SLO-aware background compaction (serve/compaction.py): headroom
decision, exponential backoff, deletion-record GC against the provable
frontier, WAL-driven checkpoint rotation — all pinned deterministically
through the ``run_cycle`` seam (no thread timing), plus one end-to-end
frontend integration."""

import os
import time

import numpy as np
import pytest

from go_crdt_playground_tpu.net.peer import Node
from go_crdt_playground_tpu.obs import Recorder
from go_crdt_playground_tpu.obs.metrics import percentile_of_counts
from go_crdt_playground_tpu.serve.compaction import CompactionScheduler

E, A = 48, 3


def _node(rec, **kw):
    return Node(0, E, A, recorder=rec, **kw)


def test_percentile_of_counts_windows():
    rec = Recorder()
    for v in (0.001,) * 90 + (2.0,) * 10:
        rec.observe("lat", v)
    h1 = rec.histogram("lat")
    assert percentile_of_counts(h1, 0.50) == pytest.approx(0.001, rel=0.5)
    assert percentile_of_counts(h1, 0.99) >= 1.0
    assert percentile_of_counts([0] * len(h1), 0.99) is None  # empty window
    # a window diff isolates RECENT behavior from cumulative history
    for v in (0.5,) * 10:
        rec.observe("lat", v)
    h2 = rec.histogram("lat")
    window = [a - b for a, b in zip(h2, h1)]
    assert percentile_of_counts(window, 0.5) == pytest.approx(0.5, rel=0.5)


def test_no_headroom_backs_off_exponentially():
    rec = Recorder()
    sched = CompactionScheduler(_node(rec), rec, interval_s=0.5,
                                queue_depth_max=4, max_backoff_s=3.0)
    rec.set_gauge("serve.queue.depth", 50)  # saturated
    waits = []
    for _ in range(4):
        out = sched.run_cycle()
        assert out["ran"] is False
        waits.append(out["backoff_s"])
    assert waits == [1.0, 2.0, 3.0, 3.0]  # doubles, then caps
    snap = rec.snapshot()
    assert snap["counters"]["compact.backoffs"] == 4
    assert snap["gauges"]["compact.headroom"] == 0.0
    # headroom returns -> the wait resets to the base interval
    rec.set_gauge("serve.queue.depth", 0)
    out = sched.run_cycle()
    assert out["ran"] is True
    assert sched._wait_s == 0.5


def test_recent_latency_spike_blocks_compaction():
    """The windowed p99 gates the cycle: an old idle history must NOT
    mask a current spike, and an old spike must not block forever."""
    rec = Recorder()
    sched = CompactionScheduler(_node(rec), rec, interval_s=0.5,
                                p99_budget_s=0.05)
    rec.set_gauge("serve.queue.depth", 0)
    for _ in range(50):
        rec.observe("serve.ingest_latency_s", 0.001)
    assert sched.run_cycle()["ran"] is True  # first window: calm
    for _ in range(20):
        rec.observe("serve.ingest_latency_s", 0.5)  # spike NOW
    assert sched.run_cycle()["ran"] is False
    assert sched.run_cycle()["ran"] is True  # spike aged out of window


def test_gc_drops_stable_deletions_and_reports_occupancy(tmp_path):
    rec = Recorder()
    node = _node(rec)
    node.add(*range(10))
    node.delete(1, 2, 3)
    # membership is DECLARED: without a declaration GC is disabled
    # (an undeclared frontier is all-zeros — restart-safe, unlike any
    # "have I heard a peer?" heuristic)
    undeclared = CompactionScheduler(node, rec, interval_s=0.5)
    rec.set_gauge("serve.queue.depth", 0)
    out = undeclared.run_cycle()
    assert out["ran"] is True and out["gc"] is None
    # the explicit isolated declaration (participants=()): this
    # replica IS the deployment, its own processed vector is the
    # frontier, every deletion record is provably stable
    sched = CompactionScheduler(node, rec, interval_s=0.5,
                                gc_participants=())
    out = sched.run_cycle()
    assert out["gc"] == {"dropped": 3, "remaining": 0}
    snap = rec.snapshot()
    assert snap["counters"]["compact.gc_runs"] == 1
    assert snap["counters"]["compact.gc_dropped_lanes"] == 3
    assert snap["gauges"]["compact.deleted_lanes"] == 0
    assert sorted(int(e) for e in node.members()) == [0] + list(range(4, 10))


def test_gc_frontier_waits_for_peer_acknowledgement():
    """Mid-fleet, a deletion record survives until every DECLARED
    participant's advertised ``processed`` vector covers it — the
    provable half of causal stability (ops/delta.gc_frontier,
    per-participant) — and an UNCONFIGURED frontier disables GC
    entirely once any peer has been heard (gossip is transitive:
    membership cannot be guessed from traffic)."""
    rec = Recorder()
    node = _node(rec)
    node.add(1, 2)
    node.delete(1)
    # a peer that has NOT processed our deletes yet advertises zeros
    import jax

    peer = Node(1, E, A)
    prow = jax.tree.map(lambda x: x[0], peer._state)
    from go_crdt_playground_tpu.net import framing as fr
    from go_crdt_playground_tpu.ops import delta as delta_ops

    payload = delta_ops.delta_extract(prow, np.zeros(A, np.uint32))
    node.apply_payload_body(fr.encode_payload_msg(
        fr.MODE_DELTA, 1, np.asarray(prow.processed), payload))
    assert node.gc_deletions(
        participants=[1])["dropped"] == 0  # peer hasn't caught up
    # the peer now advertises a processed vector covering our clock
    caught_up = np.asarray([10, 10, 10], np.uint32)
    payload = delta_ops.delta_extract(prow, np.zeros(A, np.uint32))
    node.apply_payload_body(fr.encode_payload_msg(
        fr.MODE_DELTA, 1, caught_up, payload))
    # no participant set: a node that has heard ANY peer refuses to GC
    # (a never-heard replica may hold our elements via transitive
    # gossip and would keep them forever past a dropped record)
    assert node.gc_deletions()["dropped"] == 0
    assert np.all(node.deletion_frontier() == 0)
    # an undeclared/unheard participant blocks GC too
    assert node.gc_deletions(participants=[1, 2])["dropped"] == 0
    # the declared set caught up: the record is provably stable
    assert node.gc_deletions(participants=[1])["dropped"] == 1


def test_gc_skipped_mid_heal_and_on_reference_semantics():
    rec = Recorder()
    node = _node(rec)
    node.add(1)
    node.delete(1)
    with node._lock:
        node.full_resync_pending = True
    sched = CompactionScheduler(node, rec, interval_s=0.5,
                                gc_participants=())
    rec.set_gauge("serve.queue.depth", 0)
    out = sched.run_cycle()
    assert out["ran"] is True and out["gc"] is None  # healing: no GC
    with node._lock:
        node.full_resync_pending = False
    assert sched.run_cycle()["gc"] is not None  # heal done: GC resumes
    ref = Node(0, E, A, delta_semantics="reference")
    with pytest.raises(ValueError, match="v2"):
        ref.gc_deletions()


def test_checkpoint_rotation_waits_for_wal_growth(tmp_path):
    from go_crdt_playground_tpu.utils.checkpoint import CheckpointStore
    from go_crdt_playground_tpu.utils.wal import DeltaWal

    d = str(tmp_path / "durable")
    rec = Recorder()
    node = _node(rec)
    node.wal = DeltaWal(os.path.join(d, "wal"), recorder=rec,
                        fsync=False)
    store = CheckpointStore(d, recorder=rec)
    calls = []

    def ckpt():
        calls.append(node.save_durable(store))

    sched = CompactionScheduler(node, rec, checkpoint=ckpt,
                                interval_s=0.5,
                                checkpoint_wal_bytes=200)
    sched._ckpt_base_bytes = rec.counter("wal.appended_bytes")
    rec.set_gauge("serve.queue.depth", 0)
    node.add(1)
    assert sched.run_cycle()["checkpointed"] is False  # not enough WAL
    for i in range(2, 30):
        node.add(i)
    out = sched.run_cycle()
    assert out["checkpointed"] is True
    assert calls == [1]
    assert rec.snapshot()["counters"]["compact.checkpoints"] == 1
    # rotation retired the sealed segments: replay-from-birth shrank
    assert node.wal.record_count() == 0
    # and does not re-checkpoint until the WAL grows again
    assert sched.run_cycle()["checkpointed"] is False
    with node._lock:
        node.wal.close()


def test_frontend_integration_compacts_under_idle(tmp_path):
    """End to end: a frontend with compaction enabled GCs deletion
    lanes while idle and keeps serving; the counters surface in the
    STATS dialect like every other SLO number."""
    from go_crdt_playground_tpu.serve.client import ServeClient
    from go_crdt_playground_tpu.serve.frontend import ServeFrontend

    fe = ServeFrontend(E, A, durable_dir=str(tmp_path / "n0"),
                       max_batch=8, flush_ms=1.0,
                       compact_interval_s=0.05)
    fe.serve()
    try:
        with ServeClient(fe.addr) as c:
            c.add(1, 2, 3)
            c.delete(2)
            deadline = time.monotonic() + 30.0
            dropped = 0
            while time.monotonic() < deadline:
                snap = c.stats()
                dropped = snap["counters"].get(
                    "compact.gc_dropped_lanes", 0)
                if dropped:
                    break
                time.sleep(0.05)
            assert dropped == 1, "idle frontend never GC'd the deletion"
            members, _ = c.members()
            assert members == [1, 3]
            c.add(10)  # still serving after maintenance
            members, _ = c.members()
            assert members == [1, 3, 10]
    finally:
        fe.close()
