"""Conformance suite: the reference's Go tests, ported as executable vectors.

Since no Go toolchain exists in this environment, these ports replace
``go test`` (README.md:1) as the correctness driver.  Scenario steps are
transcribed 1:1 from the reference test files (anchors cited per test);
expected memberships are the reference's own inline oracles.

Ported tests:
  T1 TestAWSetXXX                          awset_test.go:10-29
  T2 TestAWSet                             awset_test.go:31-83
  T3 TestAWSetConcurrentAddWinsOverDelete  awset_test.go:85-122
  T4 TestAWSetCommutativity                awset_test.go:124-154 (sans os.Exit)
  T6 TestAWSetDelta                        awset-delta_test.go:168-189
  T8 TestVersionVector                     crdt-misc_test.go:5-28

Plus coverage the reference lacks (SURVEY §4 gaps): unequal-length VVs,
>2 actors, has/reset, idempotence, associativity, δ-clock divergence, δ-GC.
"""

import random


from go_crdt_playground_tpu.models.spec import (
    AWSet,
    AWSetDelta,
    Dot,
    VersionVector,
)


def make_pair(cls=AWSet, **kw):
    """Two-actor fixture mirroring testAWSetInit (awset_test.go:156-198):
    A = actor 0, B = actor 1, both with pre-sized VersionVector{0,0}."""
    a = cls(actor=0, version_vector=VersionVector([0, 0]), **kw)
    b = cls(actor=1, version_vector=VersionVector([0, 0]), **kw)
    return a, b


def assert_entries(s: AWSet, *expected: str):
    """Port of the assertEntries closure (awset_test.go:175-196):
    membership-only assertion against sorted expected values."""
    assert s.sorted_values() == sorted(expected)


# ---------------------------------------------------------------------------
# T8 — TestVersionVector (crdt-misc_test.go:5-28)
# ---------------------------------------------------------------------------


def test_version_vector_join():
    a, b = VersionVector([1, 1, 0, 4]), VersionVector([2, 0, 3, 0])
    a.merge(b)
    assert a.v == [2, 1, 3, 4]
    b.merge(a)
    assert b.v == [2, 1, 3, 4]


def test_version_vector_unequal_length_extension():
    """Covers the append-extension branch (crdt-misc.go:50-52) the reference
    never tests."""
    a, b = VersionVector([1]), VersionVector([0, 5, 2])
    a.merge(b)
    assert a.v == [1, 5, 2]
    # and the shorter-src direction leaves the tail untouched
    c = VersionVector([7])
    b.merge(c)
    assert b.v == [7, 5, 2]


def test_version_vector_has_dot_and_counter_bounds():
    """crdt-misc.go:26-41 semantics, including the doc examples, with the
    out-of-range guard fixed (reference panics at d.Actor == len(vv))."""
    vv = VersionVector([1, 3, 2])
    assert vv.has_dot(Dot(1, 2))  # 3 >= 2
    assert not vv.has_dot(Dot(1, 4))  # 3 < 4
    assert not vv.has_dot(Dot(3, 1))  # actor == len(vv): never seen
    assert not vv.has_dot(Dot(7, 1))
    assert vv.counter(1) == 3
    assert vv.counter(3) == 0
    assert vv.counter(9) == 0


# ---------------------------------------------------------------------------
# T1 — TestAWSetXXX (awset_test.go:10-29)
# ---------------------------------------------------------------------------


def test_awset_xxx_concurrent_writer_wins():
    A, B = make_pair()

    A.add("A", "B", "C")
    B.add("A", "B", "C")
    A.merge(B)
    B.merge(A)
    assert_entries(A, "A", "B", "C")
    assert_entries(B, "A", "B", "C")

    A.del_("B")
    B.add("B")
    B.merge(A)
    A.merge(B)
    assert_entries(A, "A", "B", "C")
    assert_entries(B, "A", "B", "C")  # concurrent writer wins


# ---------------------------------------------------------------------------
# T2 — TestAWSet (awset_test.go:31-83)
# ---------------------------------------------------------------------------


def test_awset_long_scenario():
    A, B = make_pair()

    assert_entries(A)
    assert_entries(B)

    A.add("Shelly")
    assert_entries(A, "Shelly")
    assert_entries(B)

    B.merge(A)  # B <- A
    assert_entries(A, "Shelly")
    assert_entries(B, "Shelly")

    B.add("Bob", "Phil", "Pete")
    assert_entries(A, "Shelly")
    assert_entries(B, "Shelly", "Bob", "Phil", "Pete")

    A.merge(B)  # A <- B
    assert_entries(A, "Shelly", "Bob", "Phil", "Pete")
    assert_entries(B, "Shelly", "Bob", "Phil", "Pete")

    A.del_("Phil")
    A.add("Bob")  # update
    A.add("Anna")
    assert_entries(A, "Shelly", "Bob", "Pete", "Anna")
    assert_entries(B, "Shelly", "Bob", "Phil", "Pete")

    B.merge(A)  # B <- A
    assert_entries(A, "Shelly", "Bob", "Pete", "Anna")
    assert_entries(B, "Shelly", "Bob", "Pete", "Anna")

    A.del_("Bob", "Pete")
    B.del_("Bob", "Shelly")
    A.merge(B)  # A <- B
    B.merge(A)  # B <- A
    assert_entries(A, "Anna")
    assert_entries(B, "Anna")

    A.add("A", "B", "C")
    A.del_("A")
    A.add("A")
    B.merge(A)  # B <- A
    assert_entries(A, "Anna", "A", "B", "C")
    assert_entries(B, "Anna", "A", "B", "C")


# ---------------------------------------------------------------------------
# T3 — TestAWSetConcurrentAddWinsOverDelete (awset_test.go:85-122)
# ---------------------------------------------------------------------------


def test_concurrent_add_wins_over_delete():
    A, B = make_pair()

    A.add("Anne", "Bob")
    B.add("Anne")
    # fork state and test concurrent add and delete (awset_test.go:104-112):
    A2, B2 = A.clone(), B.clone()
    B2.add("Bob")
    A2.del_("Bob")
    B2.merge(A2)
    A2.merge(B2)
    assert_entries(B2, "Anne", "Bob")  # writer wins
    assert_entries(A2, "Anne", "Bob")

    # non-concurrent delete: delete sticks (awset_test.go:113-121)
    B.add("Bob")
    B.merge(A)  # makes the delete below causally after B's add
    A.del_("Bob")
    B.merge(A)
    A.merge(B)
    assert_entries(B, "Anne")
    assert_entries(A, "Anne")


def test_delete_becomes_concurrent_without_premerge():
    """The reference documents (awset_test.go:115) that commenting out the
    pre-delete merge flips the scenario to concurrent and 'Bob' survives.
    We pin that counterfactual as its own test."""
    A, B = make_pair()
    A.add("Anne", "Bob")
    B.add("Anne")
    B.add("Bob")
    # no B.merge(A) here -> A's delete is concurrent with B's add
    A.del_("Bob")
    B.merge(A)
    A.merge(B)
    assert_entries(B, "Anne", "Bob")
    assert_entries(A, "Anne", "Bob")


# ---------------------------------------------------------------------------
# T4 — TestAWSetCommutativity (awset_test.go:124-154, without the os.Exit(0)
# debug artifact at :153)
# ---------------------------------------------------------------------------


def test_commutativity_of_merge_order():
    A, B = make_pair()
    A.add("Shelly", "Bob", "Pete", "Anna")
    B.add("Shelly", "Bob", "Pete", "Anna")

    A.del_("Anna")
    B.add("Anna")
    assert_entries(A, "Shelly", "Bob", "Pete")
    assert_entries(B, "Shelly", "Bob", "Pete", "Anna")
    expected = ["Shelly", "Bob", "Pete", "Anna"]

    # Merge order: A -> B -> A
    A1, B1 = A.clone(), B.clone()
    B1.merge(A1)
    A1.merge(B1)
    assert_entries(A1, *expected)
    assert_entries(B1, *expected)

    # Merge order: B -> A -> B
    A.merge(B)
    B.merge(A)
    assert_entries(A, *expected)
    assert_entries(B, *expected)


# ---------------------------------------------------------------------------
# T6 — TestAWSetDelta (awset-delta_test.go:168-189)
# ---------------------------------------------------------------------------


def test_awset_delta_scenario():
    A, B = make_pair(AWSetDelta)

    A.add("A", "B")
    B.add("A", "C")
    A.merge(B)
    B.merge(A)
    assert_entries(A, "A", "B", "C")
    assert_entries(B, "A", "B", "C")

    A.del_("B")
    A.add("D", "E")
    B.add("E")
    B.merge(A)
    assert_entries(B, "A", "C", "D", "E")

    A.merge(B)
    assert_entries(A, "A", "C", "D", "E")


def test_awset_delta_clock_divergence_quirk():
    """SURVEY §3.3 [verified]: replaying TestAWSetDelta end-to-end, the
    empty-δ early return (awset-delta_test.go:60-64) leaves final VVs
    divergent — A=[5,2], B=[5,3] — even though membership converges.
    Pinned here as the strict-semantics contract."""
    A, B = make_pair(AWSetDelta)
    A.add("A", "B")
    B.add("A", "C")
    A.merge(B)
    B.merge(A)
    A.del_("B")
    A.add("D", "E")
    B.add("E")
    B.merge(A)
    A.merge(B)
    assert A.version_vector.v == [5, 2]
    assert B.version_vector.v == [5, 3]


def test_awset_delta_clocks_converge_without_strict_quirk():
    """With strict_reference_semantics=False the empty-δ path still joins
    VVs, so clocks converge with entries."""
    A, B = make_pair(AWSetDelta, strict_reference_semantics=False)
    A.add("A", "B")
    B.add("A", "C")
    A.merge(B)
    B.merge(A)
    A.del_("B")
    A.add("D", "E")
    B.add("E")
    B.merge(A)
    A.merge(B)
    assert A.version_vector == B.version_vector
    assert_entries(A, "A", "C", "D", "E")
    assert_entries(B, "A", "C", "D", "E")


def test_awset_delta_del_ticks_once_per_call():
    """δ-Del ticks the clock once per CALL (not per key) and stamps all
    deleted keys with the same dot (awset-delta_test.go:15-16,26); plain
    AWSet.del_ never ticks (awset.go:97)."""
    A, _ = make_pair(AWSetDelta)
    A.add("x", "y", "z")  # counters 1,2,3
    A.del_("x", "y")
    assert A.version_vector.v[0] == 4
    assert A.deleted["x"] == Dot(0, 4)
    assert A.deleted["y"] == Dot(0, 4)
    # clock ticks even when nothing is present to delete
    A.del_("nope")
    assert A.version_vector.v[0] == 5
    assert "nope" not in A.deleted

    P, _ = make_pair(AWSet)
    P.add("x")
    P.del_("x")
    assert P.version_vector.v[0] == 1  # no tick on delete


def test_awset_delta_resurrection_skips_obsolete_deletion():
    """MakeDeltaMergeData skips deletions masked by a later re-add
    (awset-delta_test.go:93-97)."""
    A, B = make_pair(AWSetDelta)
    A.add("k")
    B.add("q")
    A.merge(B)
    B.merge(A)  # both know each other -> δ path from now on
    A.del_("k")
    A.add("k")  # re-added: deletion obsolete
    changed, deleted = A.make_delta_merge_data(B.version_vector)
    assert changed is not None and "k" in changed
    assert deleted is None
    B.merge(A)
    assert_entries(B, "k", "q")


# ---------------------------------------------------------------------------
# Coverage beyond the reference (SURVEY §4 gaps)
# ---------------------------------------------------------------------------


def test_has_and_reset():
    A, _ = make_pair()
    assert not A.has("x")
    A.add("x")
    assert A.has("x")
    A.reset()
    assert not A.has("x")
    assert A.version_vector.v == [0, 0]  # length preserved (deviation 2)


def test_merge_idempotent():
    A, B = make_pair()
    A.add("a", "b")
    B.add("c")
    A.merge(B)
    snapshot_members = A.sorted_values()
    snapshot_vv = A.version_vector.clone()
    A.merge(A.clone())  # self-merge
    A.merge(B)  # repeat delivery
    assert A.sorted_values() == snapshot_members
    assert A.version_vector == snapshot_vv


def test_three_actor_associativity_on_membership():
    """Merging chains in any association converges on (membership, VV)
    across 3 actors — the property the butterfly all-pairs schedule
    (parallel/gossip.py) depends on."""
    rng = random.Random(7)
    for _ in range(50):
        reps = [
            AWSet(actor=i, version_vector=VersionVector([0, 0, 0]))
            for i in range(3)
        ]
        # random op soup
        universe = list("abcdefgh")
        for _ in range(30):
            r = rng.choice(reps)
            if rng.random() < 0.6:
                r.add(rng.choice(universe))
            else:
                r.del_(rng.choice(universe))
        # two different merge association orders over clones
        x = [r.clone() for r in reps]
        y = [r.clone() for r in reps]
        # order 1: chain 0<-1, 0<-2, 1<-0, 2<-0
        x[0].merge(x[1]); x[0].merge(x[2]); x[1].merge(x[0]); x[2].merge(x[0])
        # order 2: 2<-0, 1<-2, 0<-1, 2<-0, 1<-0
        y[2].merge(y[0]); y[1].merge(y[2]); y[0].merge(y[1]); y[2].merge(y[0]); y[1].merge(y[0])
        for i in range(3):
            assert x[i].converged_with(y[i]), (i, str(x[i]), str(y[i]))


def test_merge_result_independent_of_entry_order():
    """SURVEY §3.2 [verified]: merge outcome is independent of map iteration
    order.  Python dicts iterate in insertion order, so we shuffle insertion
    order and check invariance."""
    rng = random.Random(3)
    for _ in range(30):
        A, B = make_pair()
        keys = [f"k{i}" for i in range(10)]
        rng.shuffle(keys)
        A.add(*keys[:7])
        rng.shuffle(keys)
        B.add(*keys[3:])
        A.del_(*keys[:2])
        # shuffle B's entry insertion order
        items = list(B.entries.items())
        rng.shuffle(items)
        B.entries = dict(items)
        A1 = A.clone()
        A1.merge(B)
        A2 = A.clone()
        items2 = list(B.entries.items())
        rng.shuffle(items2)
        B.entries = dict(items2)
        A2.merge(B)
        assert A1.sorted_values() == A2.sorted_values()
        assert A1.version_vector == A2.version_vector


def test_delta_gc_two_replicas():
    """With gc_enabled, a deletion record is dropped once every known peer
    has acked a VV covering the deletion dot.  (Non-strict mode: under the
    strict empty-δ quirk the ack exchange itself is skipped, so the
    reference-faithful mode can never GC on a quiet channel.)"""
    A, B = make_pair(AWSetDelta, gc_enabled=True,
                     strict_reference_semantics=False)
    A.add("k")
    B.add("q")
    A.merge(B)
    B.merge(A)
    A.del_("k")
    assert "k" in A.deleted
    B.merge(A)  # B witnesses the deletion...
    assert "k" not in B.entries
    # ...and on the next exchange A learns B's ack and GCs.
    A.merge(B)
    assert A.deleted == {}


def test_delta_gc_requires_all_peers_three_replicas():
    """v2 causal-stability GC: a single peer's ack must NOT GC the record
    while a third replica that already knows our actor (δ path) hasn't
    processed the deletion — otherwise that replica keeps the entry forever
    (permanent divergence).  The processed-vector frontier only advances on
    exchanges that actually transfer deletion effects, so transitively
    learned VV counters can never fake an ack."""
    reps = [
        AWSetDelta(actor=i, version_vector=VersionVector([0, 0, 0]),
                   gc_enabled=True, delta_semantics="v2")
        for i in range(3)
    ]
    A, B, C = reps
    # Each actor performs an op so its clock is nonzero — otherwise the δ
    # dispatch (counter(src.actor) <= 0, awset-delta_test.go:53) keeps
    # taking the full-merge path, which never exchanges acks.
    A.add("k"); B.add("b"); C.add("c")
    # everyone meets everyone (full merges, then δ path onward)
    B.merge(A); C.merge(A); A.merge(B); A.merge(C); B.merge(C); C.merge(B)
    A.del_("k")
    B.merge(A)  # B sees deletion via δ payload
    assert "k" not in B.entries
    A.merge(B)  # B's ack arrives at A — but C hasn't seen the deletion
    assert "k" in A.deleted, "record must survive until C acks"
    C.merge(A)  # C sees deletion via δ payload
    assert "k" not in C.entries
    A.merge(C)  # C's ack completes the frontier
    assert "k" not in A.deleted
    # everyone converged on membership
    for r in reps:
        assert r.sorted_values() == ["b", "c"]


def _delta_trio(mode: str, **kw):
    return [
        AWSetDelta(actor=i, version_vector=VersionVector([0, 0, 0]),
                   delta_semantics=mode, **kw)
        for i in range(3)
    ]


def test_reference_delta_deletions_do_not_regossip():
    """Pinned reference-mode behavior: δ payloads carry only the sender's
    OWN-origin deletion log (awset-delta_test.go:93-102; deltaMerge never
    writes the receiver's log), so a deletion reaches a third replica only
    by direct contact with the originator.  C keeps 'k' after hearing from
    B — permanent divergence until C talks to A."""
    A, B, C = _delta_trio("reference")
    A.add("k"); B.add("b"); C.add("c")
    B.merge(A); C.merge(A); A.merge(B); A.merge(C); B.merge(C); C.merge(B)
    A.del_("k")
    B.merge(A)
    assert "k" not in B.entries
    C.merge(B)  # B cannot forward A's deletion on the δ path
    assert "k" in C.entries, "reference quirk: deletion does not re-gossip"
    C.merge(A)  # only direct contact with the originator removes it
    assert "k" not in C.entries


def test_v2_delta_deletions_regossip_transitively():
    """v2 absorbs received deletion records into the receiver's log, so C
    learns A's deletion from B without ever talking to A."""
    A, B, C = _delta_trio("v2", gc_enabled=True)
    A.add("k"); B.add("b"); C.add("c")
    B.merge(A); C.merge(A); A.merge(B); A.merge(C); B.merge(C); C.merge(B)
    A.del_("k")
    B.merge(A)
    assert "k" not in B.entries
    C.merge(B)  # deletion arrives transitively via B
    assert "k" not in C.entries
    # GC is still sound under transitive propagation: acks reflect genuine
    # processing, and once they complete everyone has converged.
    A.merge(B); A.merge(C)
    assert "k" not in A.deleted or not A.gc_enabled
    for r in (A, B, C):
        assert r.sorted_values() == ["b", "c"]


def test_reference_delta_add_wins_violation_pinned():
    """Reference δ arbitration checks the receiver's VV against the
    DELETION dot (awset-delta_test.go:153), not the sender's VV against the
    live dot (awset.go:152).  With 3 actors this deletes an entry whose
    live dot came from a concurrent add the deleter never saw — add-wins
    violated on the δ path while the full-state path preserves it.  Pinned
    as reference behavior."""
    B, C, D = _delta_trio("reference")
    B_, C_, D_ = B, C, D  # actors: B=0, C=1, D=2
    B.add("k")
    C.merge(B)            # full: C has k with dot (B,1)
    D.add("k")            # concurrent add at D, dot (D,1); D never saw B
    B.del_("k")           # B deletes, deletion dot (B,2)
    C.merge(D)            # full: C's live dot for k becomes (D,1)
    assert "k" in C.entries
    C.merge(B)            # δ path: deletion (B,2) not covered by C.vv -> removes
    assert "k" not in C.entries, "pinned: reference δ path violates add-wins"


def test_v2_delta_preserves_add_wins():
    """Same scenario as above under v2: arbitration is full-merge phase 2
    restricted to the payload keys — B's VV does not cover D's live dot, so
    the concurrent add survives."""
    B, C, D = _delta_trio("v2")
    B.add("k")
    C.merge(B)
    D.add("k")
    B.del_("k")
    C.merge(D)
    C.merge(B)
    assert "k" in C.entries, "v2 must preserve add-wins in any topology"
    assert C.entries["k"] == Dot(2, 1)


def test_full_merge_stale_dot_overwrite_can_drop_concurrent_readd():
    """Pinned reference full-state behavior: merge phase 1 unconditionally
    overwrites the dst dot even with an OLDER src dot (awset.go:142 runs for
    the 'update' case regardless of dot ordering).  A replica holding a
    fresh concurrent re-add can thus have its dot replaced by a stale one,
    after which a deleter who witnessed only the stale add removes the
    entry — the concurrent re-add is lost.  Minimal 3-actor schedule found
    by randomized search; the tensor kernel must reproduce this exactly."""
    reps = [AWSet(actor=i, version_vector=VersionVector([0, 0, 0]))
            for i in range(3)]
    R0, R1, R2 = reps
    R2.add("x")          # dot (C 1)
    R1.merge(R2)         # R1 has x@(C 1)
    R0.merge(R1)         # R0 has x@(C 1)
    R2.del_("x")         # C deletes x (no clock tick, awset.go:97)
    R0.add("x")          # concurrent re-add at A: x@(A 1)
    R0.merge(R1)         # phase 1 overwrites R0's fresh (A 1) with stale (C 1)
    assert R0.entries["x"] == Dot(2, 1)
    R0.merge(R2)         # phase 2: src witnessed (C 1) and dropped it -> remove
    assert "x" not in R0.entries, "pinned: stale-dot overwrite loses the re-add"


def test_v2_delta_network_randomized_convergence():
    """Randomized 3-replica op soups under v2 δ-sync: after closing
    all-pairs rounds the network must converge internally on
    (membership, VV).  (No cross-model comparison with full-state AWSet:
    the reference's unconditional dot overwrite makes full-state merge
    schedule-sensitive — see the stale-dot test above — so the two
    protocols legitimately disagree on some schedules.)"""
    rng = random.Random(11)
    universe = [f"k{i}" for i in range(12)]
    for _ in range(25):
        delt = _delta_trio("v2", gc_enabled=True)
        ops = []
        for _ in range(40):
            r = rng.randrange(3)
            if rng.random() < 0.55:
                ops.append(("add", r, rng.choice(universe)))
            elif rng.random() < 0.75:
                ops.append(("del", r, rng.choice(universe)))
            else:
                s = rng.randrange(3)
                if s != r:
                    ops.append(("merge", r, s))
        for op, r, x in ops:
            if op == "add":
                delt[r].add(x)
            elif op == "del":
                delt[r].del_(x)
            else:
                delt[r].merge(delt[x])
        # closing all-pairs rounds to convergence
        for _ in range(2):
            for i in range(3):
                for j in range(3):
                    if i != j:
                        delt[i].merge(delt[j])
        for i in range(1, 3):
            assert delt[i].sorted_values() == delt[0].sorted_values(), (
                ops, i, delt[i].sorted_values(), delt[0].sorted_values())
            assert delt[i].version_vector.v == delt[0].version_vector.v


def test_canonical_rendering_matches_reference_format():
    """AWSet.String / VersionVector.String / Dot.String byte format
    (awset.go:163-171, crdt-misc.go:57-68, 17-19)."""
    A, _ = make_pair()
    A.add("Alice")
    assert str(Dot(3, 2)) == "(D 2)"
    assert str(A.version_vector) == "[(A 1), (B 0)]"
    assert str(A) == '[(A 1), (B 0)]\n  (A 1)  "Alice"'
