"""Partial-persistence layer of the bench supervisor (bench.py).

Round 3 lost its whole TPU evidence session to one late hang; the fix is
per-step persistence + resume, which these tests pin without needing a
device: steps persisted by a dying child must be reloadable by a retry
child on the same platform, and never leak across platforms (a CPU
fallback's numbers must not seed a TPU artifact).
"""

import json
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import bench  # noqa: E402


@pytest.fixture(autouse=True)
def _session(monkeypatch):
    """The supervisor always assigns a session id before children run;
    tests mirror that default (unscoped = never resume, pinned below)."""
    monkeypatch.setenv("CRDT_BENCH_SESSION", "test-session")


def test_load_partial_without_session_never_resumes(tmp_path, monkeypatch):
    # unsupervised child (no session id): resuming would match any
    # unscoped stale partial left by older code — must load nothing
    path = str(tmp_path / "partial.jsonl")
    bench._persist_partial(path, "config1",
                           {"value": 1.0, "platform": "tpu"})
    monkeypatch.delenv("CRDT_BENCH_SESSION")
    assert bench._load_partial(path, "tpu") == {}


def test_persist_then_load_roundtrip(tmp_path):
    path = str(tmp_path / "partial.jsonl")
    rec1 = bench._persist_partial(path, "config1",
                                  {"value": 1.5, "platform": "tpu"})
    bench._persist_partial(path, "config2",
                           {"value": 2.5, "platform": "tpu"})
    assert rec1["_step"] == "config1"
    done = bench._load_partial(path, "tpu")
    assert set(done) == {"config1", "config2"}
    assert done["config1"]["value"] == 1.5


def test_load_partial_filters_platform(tmp_path):
    path = str(tmp_path / "partial.jsonl")
    bench._persist_partial(path, "config1",
                           {"value": 1.0, "platform": "cpu"})
    bench._persist_partial(path, "config2",
                           {"value": 2.0, "platform": "tpu"})
    assert set(bench._load_partial(path, "tpu")) == {"config2"}
    assert set(bench._load_partial(path, "cpu")) == {"config1"}


def test_load_partial_missing_file(tmp_path):
    assert bench._load_partial(str(tmp_path / "nope.jsonl"), "tpu") == {}


def test_persist_appends_latest_wins(tmp_path):
    # a retried step overwrites on load (later line wins the dict key)
    path = str(tmp_path / "partial.jsonl")
    bench._persist_partial(path, "config1",
                           {"value": 1.0, "platform": "tpu"})
    bench._persist_partial(path, "config1",
                           {"value": 9.0, "platform": "tpu"})
    assert bench._load_partial(path, "tpu")["config1"]["value"] == 9.0
    with open(path) as f:
        assert len([ln for ln in f if ln.strip()]) == 2


def test_load_partial_tolerates_torn_line(tmp_path):
    # the supervisor SIGKILLs timed-out children; a mid-write kill can
    # leave a torn trailing line, which must not wedge later attempts
    path = str(tmp_path / "partial.jsonl")
    bench._persist_partial(path, "config1",
                           {"value": 1.0, "platform": "tpu"})
    with open(path, "a") as f:
        f.write('{"value": 2.0, "platform": "tpu", "_st')
    done = bench._load_partial(path, "tpu")
    assert set(done) == {"config1"}
    assert bench._read_partial_records(path)[0]["_step"] == "config1"


def test_load_partial_filters_session(tmp_path, monkeypatch):
    # a stale partial from a killed supervisor (different session id)
    # must not seed this session's artifact
    path = str(tmp_path / "partial.jsonl")
    monkeypatch.setenv("CRDT_BENCH_SESSION", "old-1")
    bench._persist_partial(path, "config1",
                           {"value": 1.0, "platform": "tpu"})
    monkeypatch.setenv("CRDT_BENCH_SESSION", "new-2")
    bench._persist_partial(path, "config2",
                           {"value": 2.0, "platform": "tpu"})
    assert set(bench._load_partial(path, "tpu")) == {"config2"}


def test_partial_lines_are_json(tmp_path):
    path = str(tmp_path / "partial.jsonl")
    bench._persist_partial(path, "drop0.1",
                           {"drop_rate": 0.1, "rounds_median": 12,
                            "platform": "tpu"})
    with open(path) as f:
        rec = json.loads(f.read())
    assert rec["_step"] == "drop0.1"


def test_probe_child_prints_json(tmp_path):
    """The supervisor's liveness probe (bench.py --probe) must print one
    JSON line naming the backend it reached and exit 0 — on a CPU-pinned
    env here; the driver path runs it against the ambient TPU tunnel
    before committing to any full-length measurement attempt."""
    import subprocess

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    from __graft_entry__ import _scrubbed_cpu_env

    env = dict(_scrubbed_cpu_env(1), CRDT_BENCH_CHILD="1")
    proc = subprocess.run(
        [sys.executable,
         str(Path(bench.__file__).resolve()), "--probe"],
        env=env, timeout=120, capture_output=True, text=True,
        cwd=str(tmp_path))
    assert proc.returncode == 0, proc.stderr
    rec = json.loads(proc.stdout.strip())
    assert rec["probe"] == "cpu"
    assert rec["dispatch_s"] >= 0.0
    assert not list(tmp_path.iterdir())  # probe writes no artifacts


def test_time_drop_round_compiles_and_runs():
    """The droprate capture's on-chip timing program must compile and
    execute on CPU CI: it only ever ran under on_tpu before, so a break
    surfaced at the END of a live TPU session (after the convergence
    sweeps) — the most expensive possible place to find it."""
    import jax.numpy as jnp

    from go_crdt_playground_tpu.parallel import gossip

    state0 = bench.build_state(96, 32, 8)
    offsets = jnp.asarray(gossip.dissemination_offsets(96), jnp.uint32)
    for rate in (0.0, 0.3):
        # tiny scan: this proves compile+execute, not a stable rate
        per_round = bench._time_drop_round(state0, offsets, rate, 96,
                                           start=4, min_delta=1e-4,
                                           repeats=1)
        assert per_round > 0.0


def test_northstar_ici_model_math():
    """The v5e-4 projection must be a traffic model, not linear scaling
    (VERDICT r4 weakness #3): block-aligned dissemination offsets ship
    whole packed blocks over the ring cut; intra-block offsets are free.
    Pins the arithmetic at the north-star shape."""
    m = bench.northstar_ici_model(1.2, 1 << 20, 256, 256, n_chips=4)
    # PackedAWSetDeltaState row: vv+processed (2*256*4) + 4 dot arrays
    # (4*256*4) + 2 bitpacked membership rows (2*32) + actor (4)
    assert m["packed_row_bytes"] == 2 * 256 * 4 + 4 * 256 * 4 + 64 + 4
    # 20 offsets, blk=2^18: only 2^18 (1 hop) and 2^19 (2 hops) cross
    assert [c["offset"] for c in m["crossing_rounds"]] == [1 << 18, 1 << 19]
    assert [c["ring_hops"] for c in m["crossing_rounds"]] == [1, 2]
    assert m["ici_link_bytes"] == (1 << 18) * m["packed_row_bytes"] * 3
    assert m["compute_s"] == 0.3
    assert m["ici_s"] == round(m["ici_link_bytes"] / 45e9, 4)
    assert m["model_s"] == max(m["compute_s"], m["ici_s"])
    assert m["serialized_bound_s"] == round(m["compute_s"] + m["ici_s"], 4)
    # ICI-bound regime: with 64 chips compute shrinks and the ring cut
    # dominates, so the model must NOT report the linear number
    m64 = bench.northstar_ici_model(1.2, 1 << 20, 256, 256, n_chips=64)
    assert m64["model_s"] == m64["ici_s"] > m64["compute_s"]


def test_new_ladder_steps_run_at_tiny_shapes(monkeypatch):
    """The round-5 ladder steps (dot-word configs, AWSet-only config 5)
    must run end-to-end at tiny shapes in CI — a signature or dispatch
    break must not first surface mid-capture in a live TPU window."""
    orig = bench._scan_round_rate

    def quick(*a, **k):
        k.update(min_delta=1e-3, max_n=32, repeats=2)
        return orig(*a, **k)

    monkeypatch.setattr(bench, "_scan_round_rate", quick)
    r3 = bench.measure_config3_dotpacked(128, 64, 64)
    r4 = bench.measure_config4_dotpacked(128, 64, 64)
    r5 = bench.measure_config5_awset(256, 64, 64)
    for r in (r3, r4, r5):
        assert r["value"] > 0, r["metric"]
        assert r["repeats"] >= 1


def test_salvage_headline_prefers_session_tpu_record(tmp_path, monkeypatch,
                                                     capsys):
    """A bool-layout TPU headline persisted by a child later killed in
    the optional dot-word attempt must be salvaged (not downgraded to a
    CPU fallback), consuming the partial file."""
    monkeypatch.chdir(tmp_path)
    bench._persist_partial(bench._HEADLINE_PARTIAL, "headline",
                           {"metric": "m", "value": 80.0,
                            "platform": "tpu", "layout": "bool"})
    assert bench._salvage_headline(["attempt1(timeout)"]) is True
    rec = json.loads(capsys.readouterr().out.strip())
    assert rec["value"] == 80.0
    assert rec["platform"] == "tpu"
    assert "_session" not in rec and "_step" not in rec
    assert "salvaged" in rec["note"] and "attempt1(timeout)" in rec["note"]
    assert not (tmp_path / bench._HEADLINE_PARTIAL).exists()


def test_salvage_headline_rejects_cpu_and_foreign_sessions(tmp_path,
                                                           monkeypatch,
                                                           capsys):
    monkeypatch.chdir(tmp_path)
    # cpu record: never salvaged into a headline
    bench._persist_partial(bench._HEADLINE_PARTIAL, "headline",
                           {"metric": "m", "value": 1.0, "platform": "cpu"})
    assert bench._salvage_headline([]) is False
    assert not (tmp_path / bench._HEADLINE_PARTIAL).exists()
    # foreign-session tpu record: predates this supervisor run
    monkeypatch.setenv("CRDT_BENCH_SESSION", "other")
    bench._persist_partial(bench._HEADLINE_PARTIAL, "headline",
                           {"metric": "m", "value": 2.0, "platform": "tpu"})
    monkeypatch.setenv("CRDT_BENCH_SESSION", "test-session")
    assert bench._salvage_headline([]) is False
    assert capsys.readouterr().out.strip() == ""
    # absent file
    assert bench._salvage_headline([]) is False


def test_run_ladder_executes_new_steps_first_writes_canonical(tmp_path,
                                                              monkeypatch,
                                                              capsys):
    """Execution order puts the never-captured round-5 steps first (a
    ~15-min tunnel window must land missing evidence before
    re-measuring committed configs), while the artifact keeps canonical
    config order."""
    monkeypatch.chdir(tmp_path)
    order = []

    def mk(name):
        def fn(*a, **k):
            order.append(name)
            return {"metric": f"{name}: stub", "value": 1.0, "unit": "x"}
        return fn

    for name, attr in [("config1", "measure_config1"),
                       ("config2", "measure_config2"),
                       ("config3_dotpacked", "measure_config3_dotpacked"),
                       ("config4", "measure_config4"),
                       ("config4_dotpacked", "measure_config4_dotpacked"),
                       ("config4ref", "measure_config4_reference"),
                       ("config5", "measure_config5"),
                       ("config5_awset", "measure_config5_awset")]:
        monkeypatch.setattr(bench, attr, mk(name))
    monkeypatch.setattr(bench, "measure_spec_baseline",
                        lambda full=True: (1.0, [1.0]))
    monkeypatch.setattr(bench, "measure_tpu",
                        lambda full=False: (1.0, {}) if full else 1.0)
    results = bench.run_ladder()
    assert order[:4] == ["config3_dotpacked", "config4_dotpacked",
                        "config4ref", "config5_awset"]
    mets = [r["metric"].split(":")[0] for r in results]
    assert mets == ["config1", "config2", "config3", "config3_dotpacked",
                    "config4", "config4_dotpacked", "config4ref",
                    "config5", "config5_awset"]
    assert (tmp_path / "BENCH_LADDER.json").exists()
    assert not (tmp_path / bench._LADDER_PARTIAL).exists()


def test_driver_preempts_capture_group(monkeypatch, tmp_path):
    """The driver's bench run must kill an active capture process group
    (chip arbitration: an unattended capture sharing the TPU would
    halve the judged headline) and clean up stale markers."""
    import os
    import subprocess
    import time

    cap = str(tmp_path / "capture.active")
    drv = str(tmp_path / "driver.active")
    monkeypatch.setattr(bench, "_CAPTURE_MARKER", cap)
    monkeypatch.setattr(bench, "_DRIVER_MARKER", drv)
    p = subprocess.Popen(["sleep", "30"], start_new_session=True)
    Path(cap).write_text(str(p.pid))
    bench._preempt_capture()
    time.sleep(0.5)
    assert p.poll() is not None
    assert not Path(cap).exists()
    bench._post_driver_marker()
    assert Path(drv).read_text() == str(os.getpid())
    # stale marker: a REAL dead pgid (own session, reaped) — a literal
    # like 999999 could name a live group under a raised pid_max and
    # the preempt would kill an unrelated process
    dead = subprocess.Popen(["true"], start_new_session=True)
    dead.wait()
    Path(cap).write_text(str(dead.pid))
    bench._preempt_capture()
    assert not Path(cap).exists()


def test_roofline_row_bytes_and_artifact(tmp_path, monkeypatch, capsys):
    """The static HBM model's row-bytes must match the regime notes'
    audited figures (BASELINE.md config 3: 3,328 B/row bool, 100.3MB
    aligned round; DESIGN 11: ~2.1KB dot-word, ~6.7KB delta bool)."""
    assert bench._row_bytes(256, 256, "awset", "bool") == 3328
    assert bench._row_bytes(256, 256, "awset", "dots") == 2080
    assert bench._row_bytes(256, 256, "delta", "bool") == 6656
    assert bench._row_bytes(256, 256, "delta", "dots") == 4160
    monkeypatch.chdir(tmp_path)   # no BENCH_LADDER.json here
    out = bench.run_roofline()
    assert (tmp_path / "ROOFLINE.json").exists()
    by_cfg = {r["config"]: r for r in out["rows"]}
    assert by_cfg["config3"]["aligned_round_mb"] == 100.3
    assert by_cfg["config3"]["roofline_round_ms"] == 0.1225
    assert by_cfg["config3_dotpacked"]["roofline_rate"] > \
        by_cfg["config3"]["roofline_rate"] * 1.5
    assert "measured_rate" not in by_cfg["config3"]
    json.loads(capsys.readouterr().out.strip())


def test_ingest_ladder_refuses_cpu_overwrite_of_tpu_artifact(tmp_path,
                                                             monkeypatch,
                                                             capsys):
    """The BENCH_r03/r05 footgun, fenced for the ingest ladder: a
    CPU(-fallback) run must refuse to overwrite an on-chip
    BENCH_INGEST.json — and must still write a fresh or same-platform
    artifact."""
    out = str(tmp_path / "BENCH_INGEST.json")
    with open(out, "w") as f:
        json.dump({"platform": "tpu", "curve": [{"committed": True}]}, f)
    # measure_ingest monkeypatched out: the guard must trip BEFORE any
    # measurement (a refused run should not even initialize legs)
    monkeypatch.setattr(bench, "measure_ingest",
                        lambda *a, **k: pytest.fail("measured anyway"))
    assert bench.run_ingest(out=out) is None
    with open(out) as f:
        assert json.load(f)["curve"] == [{"committed": True}]
    assert "refusing" in capsys.readouterr().out

    # same-platform (cpu over cpu) proceeds
    with open(out, "w") as f:
        json.dump({"platform": "cpu"}, f)
    monkeypatch.setattr(
        bench, "measure_ingest",
        lambda *a, **k: [{"batch": 8, "keys_per_op": 1,
                          "wal_bytes_ratio": 4.0}])
    art = bench.run_ingest(out=out)
    assert art["platform"] == "cpu"
    with open(out) as f:
        assert json.load(f)["curve"][0]["batch"] == 8
