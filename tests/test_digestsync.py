"""Digest-driven anti-entropy protocol tier (net/digestsync.py).

The wire-level pins of DESIGN.md §19: a QUIESCENT pair exchanges
digests + vv and zero state lanes; a DIVERGENT pair ships only the
lanes of mismatched digest groups; vv-divergence-without-digest-
mismatch falls back to the δ ladder (the collision healing rung);
legacy peers negotiate down to FULL/DELTA; digest-applied payloads are
WAL-logged and replay; and the supervisor regime converges a fleet.
"""

import numpy as np
import pytest

from go_crdt_playground_tpu.net import digestsync, framing
from go_crdt_playground_tpu.net.digestsync import (DigestNegotiator,
                                                   DigestUnsupported,
                                                   sync_digest)
from go_crdt_playground_tpu.net.framing import (MODE_DELTA, MODE_DIGEST,
                                                MODE_FULL)
from go_crdt_playground_tpu.net.peer import Node
from go_crdt_playground_tpu.obs import Recorder

E, A = 256, 4  # 4 digest groups of 64


def _pair(recorders=False, e=E):
    recs = [Recorder(), Recorder()] if recorders else [None, None]
    a = Node(0, e, A, recorder=recs[0])
    b = Node(1, e, A, recorder=recs[1])
    return a, b, recs


def _converge(a, b, addr):
    """Digest rounds until fixpoint (bounded)."""
    for _ in range(4):
        st = sync_digest(a, addr)
        if st.quiescent:
            return
    raise AssertionError("pair failed to reach a quiescent round")


def test_summary_codec_roundtrip():
    vv = np.asarray([3, 0, 9, 1], np.uint32)
    proc = np.asarray([2, 0, 9, 1], np.uint32)
    digs = np.arange(4, dtype=np.uint32) * 0x1234567
    body = digestsync.encode_summary(2, E, 64, vv, proc, digs)
    actor, gs, vv2, proc2, digs2 = digestsync.decode_summary(body, E, A)
    assert (actor, gs) == (2, 64)
    np.testing.assert_array_equal(vv, vv2)
    np.testing.assert_array_equal(proc, proc2)
    np.testing.assert_array_equal(digs, digs2)
    with pytest.raises(framing.ProtocolError, match="universe"):
        digestsync.decode_summary(body, E + 1, A)
    with pytest.raises(framing.ProtocolError):
        digestsync.decode_summary(body[:-2], E, A)  # truncated digests


def test_digest_payload_mode_roundtrip():
    """MODE_DIGEST payload bodies carry the index-lane form and decode
    through the same decode_payload_msg as every other mode."""
    import jax

    a, _, _ = _pair()
    a.add(3, 70, 200)
    a.delete(70)
    me = jax.tree.map(lambda x: x[0], a._state)
    from go_crdt_playground_tpu.ops import delta as delta_ops
    import jax.numpy as jnp

    p = delta_ops.delta_extract(me, jnp.zeros(A, jnp.uint32))
    body = framing.encode_payload_msg(MODE_DIGEST, 0,
                                      np.asarray(me.processed), p)
    mode, p2 = framing.decode_payload_msg(body, E, A)
    assert mode == MODE_DIGEST
    np.testing.assert_array_equal(np.asarray(p.changed),
                                  np.asarray(p2.changed))
    np.testing.assert_array_equal(np.asarray(p.ch_dc),
                                  np.asarray(p2.ch_dc))
    np.testing.assert_array_equal(np.asarray(p.deleted),
                                  np.asarray(p2.deleted))
    # the index form is O(diff): 3 touched lanes cost far less than
    # the dense form's two E/8 bitmasks
    dense = framing.encode_payload_msg(MODE_DELTA, 0,
                                       np.asarray(me.processed), p)
    assert len(body) < len(dense) - 2 * (E // 8) + 16


def test_divergent_pair_ships_only_mismatched_lanes():
    a, b, recs = _pair(recorders=True)
    a.add(*range(0, 8))        # group 0
    b.add(*range(64, 70))      # group 1
    addr = b.serve()
    try:
        st = sync_digest(a, addr)
    finally:
        b.close()
    assert st.mode_sent == MODE_DIGEST
    assert st.groups_mismatched == 2       # groups 0 and 1 differ
    assert st.lanes_sent == 8              # only a's group-0/1 lanes
    assert sorted(a.members().tolist()) == list(range(8)) + \
        list(range(64, 70))
    assert sorted(b.members().tolist()) == sorted(a.members().tolist())
    np.testing.assert_array_equal(a.vv(), b.vv())
    # groups 2..3 were equal: nothing from them crossed the wire —
    # the server shipped only ITS mismatched lanes too
    assert recs[1].counter("digest.lanes_sent") == 6


def test_quiescent_pair_ships_zero_state_lanes():
    a, b, recs = _pair(recorders=True)
    a.add(1, 2, 100)
    a.delete(2)
    addr = b.serve()
    try:
        _converge(a, b, addr)
        base_bytes = (recs[0].counter("digest.bytes_sent")
                      + recs[1].counter("digest.bytes_sent"))
        lanes_before = (recs[0].counter("digest.lanes_sent")
                        + recs[1].counter("digest.lanes_sent"))
        for _ in range(5):
            st = sync_digest(a, addr)
            assert st.quiescent and st.lanes_sent == 0
            assert st.mode_sent == MODE_DIGEST
        lanes_after = (recs[0].counter("digest.lanes_sent")
                       + recs[1].counter("digest.lanes_sent"))
        assert lanes_after == lanes_before  # ZERO state lanes shipped
        assert recs[0].counter("digest.quiescent") >= 5
        # bytes/quiescent round ≈ digest + vv only: 2 summaries
        # (G*4 digest bytes + 2 vv sections each) + 2 near-empty lane
        # payloads — far below one dense δ round's 4 E/8 bitmasks
        per_round = (recs[0].counter("digest.bytes_sent")
                     + recs[1].counter("digest.bytes_sent")
                     - base_bytes) / 5
        assert per_round < 4 * (E // 8)
    finally:
        b.close()


def test_deletion_heavy_quiescence_beats_delta_ladder():
    """The δ ladder re-ships the whole un-resurrected deletion log
    every round (reference wire semantics); a converged digest pair
    ships none of it — the sync-bandwidth wall the regime exists to
    break."""
    a, b, recs = _pair(recorders=True)
    a.add(*range(32))
    a.delete(*range(16))
    addr = b.serve()
    try:
        _converge(a, b, addr)
        r0 = (recs[0].counter("digest.bytes_sent")
              + recs[1].counter("digest.bytes_sent"))
        st = sync_digest(a, addr)
        digest_round = (recs[0].counter("digest.bytes_sent")
                        + recs[1].counter("digest.bytes_sent") - r0)
        assert st.quiescent
        # the same converged pair over the legacy ladder:
        s0 = (recs[0].counter("sync.bytes_sent")
              + recs[1].counter("sync.bytes_sent"))
        a.sync_with(addr)
        delta_round = (recs[0].counter("sync.bytes_sent")
                       + recs[1].counter("sync.bytes_sent") - s0)
        assert digest_round < delta_round
    finally:
        b.close()


def test_vv_only_divergence_falls_back_to_delta():
    """Same lanes, different clocks (an empty-effect op): the digests
    agree, the vvs do not — the round must ride the δ ladder (the
    collision-healing rung) and JOIN the clocks."""
    a, b, _ = _pair(recorders=False)
    a.add(1)
    addr = b.serve()
    try:
        _converge(a, b, addr)
        # a delete of an ABSENT element ticks a's clock but touches no
        # lane (del_elements: unconditional tick, empty hit mask) —
        # lanes stay identical while the vvs diverge
        a.delete(200)
        rec = Recorder()
        a.recorder = rec
        st = sync_digest(a, addr)
        assert st.mode_sent in (MODE_DELTA, MODE_FULL)
        assert rec.counter("digest.fallback_delta") == 1
        np.testing.assert_array_equal(a.vv(), b.vv())
        st2 = sync_digest(a, addr)
        assert st2.quiescent
    finally:
        b.close()


def test_legacy_peer_negotiates_down():
    """A server that only speaks the HELLO ladder answers MSG_DIGEST
    with "expected HELLO" — surfaced as DigestUnsupported, and the
    supervisor-side negotiator pins the peer legacy."""
    a, b, _ = _pair()

    # simulate a pre-digest peer: serve connections through the OLD
    # dispatch (no MSG_DIGEST branch) by monkeypatching the handler
    import types

    from go_crdt_playground_tpu.net.framing import (MSG_HELLO,
                                                    MSG_PAYLOAD)

    def legacy_serve_conn(self, conn):
        try:
            with conn:
                conn.settimeout(self.conn_timeout_s)
                msg_type, body = framing.recv_frame(
                    conn, timeout=self.hello_timeout_s)
                if msg_type != MSG_HELLO:
                    framing.send_frame(
                        conn, framing.MSG_ERROR,
                        f"expected HELLO, got {msg_type}".encode())
                    return
                peer_actor, peer_vv = framing.decode_hello(
                    body, self.num_elements, self.num_actors)
                framing.send_frame(conn, MSG_HELLO, framing.encode_hello(
                    self.actor, self.num_elements, self.vv()))
                msg_type, body = framing.recv_frame(
                    conn, timeout=self.conn_timeout_s)
                with self._lock:
                    self._apply_msg(body)
                    _, reply = self._extract_msg(peer_vv)
                framing.send_frame(conn, MSG_PAYLOAD, reply)
        except Exception:  # noqa: BLE001 — test double
            pass

    b._serve_conn = types.MethodType(legacy_serve_conn, b)
    a.add(5)
    addr = b.serve()
    neg = DigestNegotiator()
    try:
        with pytest.raises(DigestUnsupported):
            sync_digest(a, addr)
        # the supervisor's fallback: pin legacy, ride the ladder
        neg.mark_legacy(addr)
        assert not neg.use_digest(addr)
        a.sync_with(addr)
        assert sorted(b.members().tolist()) == [5]
    finally:
        b.close()


def test_digest_payloads_are_wal_logged_and_replay(tmp_path):
    """A lane payload applied over a digest exchange is durably logged
    before the state mutates and replays through restore_durable —
    MODE_DIGEST rides the §14 contract unchanged."""
    import os

    from go_crdt_playground_tpu.utils.wal import DeltaWal

    d = str(tmp_path / "durable")
    rec = Recorder()
    b = Node(1, E, A, recorder=rec,
             wal=DeltaWal(os.path.join(d, "wal"), recorder=rec))
    a = Node(0, E, A)
    a.add(3, 9, 70)
    a.delete(9)
    addr = b.serve()
    try:
        sync_digest(a, addr)
    finally:
        b.close()
    live = b.state_slice()
    with b._lock:
        b.wal.close()
    back = Node.restore_durable(d, fallback_init=lambda: Node(1, E, A))
    import jax

    for name in live._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(live, name)),
            np.asarray(getattr(back.state_slice(), name)), err_msg=name)
    assert sorted(back.members().tolist()) == [3, 70]
    back.wal.close()
    del jax


def test_quiescent_rounds_feed_gc_evidence():
    """Zero-payload digest rounds still advance the deletion-GC
    frontier: the peer's processed vector rides the summary
    (Node.note_peer_processed)."""
    a, b, _ = _pair()
    a.add(1, 2)
    a.delete(1)
    addr = b.serve()
    try:
        _converge(a, b, addr)
        frontier = a.deletion_frontier(participants=[1])
        assert frontier.any(), "peer evidence missing after digest sync"
        assert a.gc_deletions(participants=[1])["dropped"] == 1
    finally:
        b.close()


def test_supervisor_digest_regime_converges_fleet():
    from go_crdt_playground_tpu.net.antientropy import SyncSupervisor
    from go_crdt_playground_tpu.utils.backoff import BackoffPolicy

    n, e = 3, 192
    recs = [Recorder() for _ in range(n)]
    nodes = [Node(i, e, n, recorder=recs[i]) for i in range(n)]
    addrs = [nd.serve() for nd in nodes]
    for i, nd in enumerate(nodes):
        nd.add(*range(i * 16, (i + 1) * 16))
    sups = []
    try:
        for i in range(n):
            peers = [addrs[j] for j in range(n) if j != i]
            sups.append(SyncSupervisor(
                nodes[i], peers, sync_mode="digest",
                policy=BackoffPolicy(base_s=0.005, cap_s=0.02,
                                     max_retries=1),
                sync_timeout_s=5.0, interval_s=0.0,
                recorder=recs[i], seed=7 + i))
        expected = set(range(16 * n))
        for _ in range(6):
            for s in sups:
                s.sync_round()
            if all(set(nd.members().tolist()) == expected
                   for nd in nodes):
                break
        assert all(set(nd.members().tolist()) == expected
                   for nd in nodes)
        vv0 = nodes[0].vv()
        for _ in range(3):     # settle clocks, then assert quiescence
            for s in sups:
                s.sync_round()
        vv0 = nodes[0].vv()
        assert all(np.array_equal(nd.vv(), vv0) for nd in nodes)
        lanes0 = sum(r.counter("digest.lanes_sent") for r in recs)
        for _ in range(2):
            for s in sups:
                s.sync_round()
        assert sum(r.counter("digest.lanes_sent")
                   for r in recs) == lanes0
        assert sum(r.counter("digest.quiescent") for r in recs) > 0
        assert sum(r.counter("sync.exchanges") for r in recs) == 0
    finally:
        for s in sups:
            s.stop(timeout=1.0)
        for nd in nodes:
            nd.close()


def test_supervisor_refuses_digest_on_reference_semantics():
    from go_crdt_playground_tpu.net.antientropy import SyncSupervisor

    node = Node(0, 32, 2, delta_semantics="reference")
    with pytest.raises(ValueError, match="v2"):
        SyncSupervisor(node, [], sync_mode="digest")
    with pytest.raises(ValueError, match="sync_mode"):
        SyncSupervisor(Node(0, 32, 2), [], sync_mode="bogus")


# ---------------------------------------------------------------------------
# adaptive group size (ROADMAP digest rung b)
# ---------------------------------------------------------------------------


def test_server_adopts_client_group_size():
    """The server answers at the CLIENT's group size (any allowed
    rung) — the client owns the adaptation; a divergent pair converges
    identically at every rung."""
    for gs in (16, 32, 128):
        a, b, _ = _pair()
        b.add(3, 70, 200)
        addr = b.serve("127.0.0.1", 0)
        try:
            st = sync_digest(a, addr, group_size=gs)
            assert st.groups_mismatched > 0
            st = sync_digest(a, addr, group_size=gs)
            assert st.quiescent, (gs, st)
            assert sorted(a.members()) == [3, 70, 200]
        finally:
            b.close()


def test_server_refuses_off_ladder_group_size():
    """A size outside ALLOWED_GROUP_SIZES is a deterministic config
    error (it may not divide the Pallas lane width), answered as a
    protocol failure like a universe mismatch."""
    a, b, _ = _pair()
    addr = b.serve("127.0.0.1", 0)
    try:
        with pytest.raises(framing.RemoteError, match="group-size"):
            sync_digest(a, addr, group_size=48)
    finally:
        b.close()


def test_group_size_tradeoff_moves_the_right_way():
    """The tradeoff the tuner exists to walk, pinned mechanically:
    growing the group size SHRINKS the every-round summary bytes,
    while for one divergent lane amid a dense live region it GROWS
    the lanes dragged onto the wire (the whole mismatched group
    ships)."""
    seed_node = Node(2, E, A)
    for e in range(0, 120):
        seed_node.add(e)
    body = seed_node.extract_slice(np.ones(E, bool))

    assert len(digestsync.node_summary(seed_node, 128)) < \
        len(digestsync.node_summary(seed_node, 32)) < \
        len(digestsync.node_summary(seed_node, 16))

    lanes = {}
    for gs in (16, 128):
        server = Node(3, E, A)
        server.apply_payload_body(body)
        addr = server.serve("127.0.0.1", 0)
        try:
            client = Node(2, E, A)
            client.apply_payload_body(body)
            client.add(121)  # one divergent lane beside the live block
            st = sync_digest(client, addr, group_size=gs)
            assert st.groups_mismatched == 1
            lanes[gs] = st.lanes_sent
        finally:
            server.close()
    assert lanes[128] > lanes[16] > 0, lanes


def test_adaptive_ladder_streaks():
    """Grow on sustained quiescence, shrink on sustained sparse
    divergence, ignore δ-fallback rounds, respect pins and bounds."""
    from go_crdt_playground_tpu.net.digestsync import (AdaptiveGroupSize,
                                                       DigestSyncStats)

    ad = AdaptiveGroupSize(E)
    p = ("127.0.0.1", 9999)

    def stats(groups, lanes, mode=MODE_DIGEST):
        return DigestSyncStats(0, 0, mode, mode, lanes, groups,
                               groups == 0 and lanes == 0)

    assert ad.size(p) == 64  # DIGEST_GROUP_LANES default
    moves = [ad.observe(p, stats(0, 0)) for _ in range(4)]
    assert moves == ["hold"] * 3 + ["grow"] and ad.size(p) == 128
    # at the top rung, further quiescence holds
    assert [ad.observe(p, stats(0, 0)) for _ in range(5)] \
        == ["hold"] * 5
    assert ad.size(p) == 128
    # sustained sparse divergence (1 of 2 groups at gs=128 is NOT
    # sparse; 1 of 16 at gs=16 is — use the fraction rule at 128:
    # total groups = 2, max(1, 2//8)=1, so 1 mismatched group counts)
    moves = [ad.observe(p, stats(1, 3)) for _ in range(2)]
    assert moves == ["hold", "shrink"] and ad.size(p) == 64
    # DENSE divergence moves nothing (coarse is right when most of
    # the state ships anyway)
    total = digestsync.num_groups(E, 64)
    assert ad.observe(p, stats(total, 200)) == "hold"
    assert ad.size(p) == 64
    # δ-fallback rounds carry no digest evidence
    assert ad.observe(p, stats(0, 50, mode=MODE_DELTA)) == "hold"
    # pin wins forever (the pre-adaptive-server negotiation outcome)
    ad.pin(p, 64)
    for _ in range(10):
        assert ad.observe(p, stats(0, 0)) == "hold"
    assert ad.size(p) == 64
    # a second peer adapts independently
    q = ("127.0.0.1", 9998)
    assert ad.size(q) == 64
    with pytest.raises(ValueError):
        AdaptiveGroupSize(E, initial=48)


def test_supervisor_adapts_group_size_online():
    """End to end through the supervisor: a quiescent peer's group
    size grows (summary bytes per round shrink), and the gauge +
    transition counters record it."""
    from go_crdt_playground_tpu.net.antientropy import SyncSupervisor

    rec = Recorder()
    a = Node(0, E, A, recorder=rec)
    b = Node(1, E, A)
    b.add(1, 2, 3)
    addr = b.serve("127.0.0.1", 0)
    sup = SyncSupervisor(a, [addr], sync_mode="digest", recorder=rec)
    try:
        for _ in range(8):
            sup.sync_round()
        assert rec.counter("digest.group_grow") >= 1
        assert sup._group_adapter.size(addr) > 64
        assert rec.snapshot()["gauges"]["digest.group_size"] > 64
        assert sorted(a.members()) == [1, 2, 3]
    finally:
        sup.stop(timeout=1.0)
        b.close()
