"""Tensor merge-kernel conformance: the JAX path must match the executable
spec bit-for-bit — same membership, same VVs, same per-entry dots, and the
same canonical rendering — on the reference's own scenarios and on
randomized op soups (the first conformance gate of SURVEY §7.2).
"""

import random

import numpy as np
import pytest

from go_crdt_playground_tpu.models import awset
from go_crdt_playground_tpu.models.spec import AWSet, VersionVector
from go_crdt_playground_tpu.ops import merge as merge_ops
from go_crdt_playground_tpu.utils.codec import (
    ElementDict,
    pack_awsets,
    render_packed,
)


class DualWorld:
    """Runs the same op sequence on the spec dict model and the packed
    tensor path, asserting bitwise equality after every step."""

    def __init__(self, num_replicas=2, num_elements=16, num_actors=None):
        A = num_actors if num_actors is not None else num_replicas
        self.A = A
        self.spec = [
            AWSet(actor=i, version_vector=VersionVector([0] * A))
            for i in range(num_replicas)
        ]
        self.state = awset.init(num_replicas, num_elements, A)
        self.dictionary = ElementDict(capacity=num_elements)

    def add(self, r, *keys):
        self.spec[r].add(*keys)
        for k in keys:
            e = self.dictionary.encode(k)
            self.state = awset.add_element(
                self.state, np.uint32(r), np.uint32(e))

    def del_(self, r, *keys):
        self.spec[r].del_(*keys)
        for k in keys:
            if k in self.dictionary:
                e = self.dictionary.encode(k)
                self.state = awset.del_element(
                    self.state, np.uint32(r), np.uint32(e))

    def merge(self, dst, src):
        self.spec[dst].merge(self.spec[src])
        self.state, _ = merge_ops.merge_one_into(
            self.state, dst, self.state, src)

    def check(self, context=""):
        packed = pack_awsets(self.spec, self.dictionary, self.A)
        actual = awset.to_arrays(self.state)
        for name in ("vv", "present", "dot_actor", "dot_counter", "actor"):
            assert np.array_equal(packed[name], np.asarray(actual[name])), (
                context, name, packed[name], np.asarray(actual[name]))
        # byte-identical canonical rendering (awset.go:163-171 format)
        assert render_packed(actual, self.dictionary) == [
            str(s) for s in self.spec
        ], context

    def members(self, r):
        arr = awset.to_arrays(self.state)
        return sorted(
            self.dictionary.decode(int(e))
            for e in np.nonzero(arr["present"][r])[0]
        )


def test_kernel_awset_xxx():
    """TestAWSetXXX (awset_test.go:10-29) on the tensor path."""
    w = DualWorld()
    w.add(0, "A", "B", "C"); w.add(1, "A", "B", "C"); w.check()
    w.merge(0, 1); w.check()
    w.merge(1, 0); w.check()
    w.del_(0, "B"); w.add(1, "B"); w.check()
    w.merge(1, 0); w.check()
    w.merge(0, 1); w.check()
    assert w.members(0) == ["A", "B", "C"]
    assert w.members(1) == ["A", "B", "C"]


def test_kernel_awset_long_scenario():
    """TestAWSet (awset_test.go:31-83) on the tensor path, checking bitwise
    state equality after every op."""
    w = DualWorld()
    w.add(0, "Shelly"); w.check("add Shelly")
    w.merge(1, 0); w.check("B<-A")
    w.add(1, "Bob", "Phil", "Pete"); w.check()
    w.merge(0, 1); w.check("A<-B")
    w.del_(0, "Phil"); w.add(0, "Bob"); w.add(0, "Anna"); w.check()
    w.merge(1, 0); w.check("B<-A 2")
    w.del_(0, "Bob", "Pete"); w.del_(1, "Bob", "Shelly"); w.check()
    w.merge(0, 1); w.check("A<-B 2")
    w.merge(1, 0); w.check("B<-A 3")
    assert w.members(0) == ["Anna"]
    w.add(0, "A", "B", "C"); w.del_(0, "A"); w.add(0, "A"); w.check()
    w.merge(1, 0); w.check("B<-A 4")
    assert w.members(1) == ["A", "Anna", "B", "C"]


def test_kernel_concurrent_add_wins():
    """TestAWSetConcurrentAddWinsOverDelete fork scenario
    (awset_test.go:101-112): state forking is trivial on the tensor path —
    arrays are immutable values."""
    w = DualWorld()
    w.add(0, "Anne", "Bob"); w.add(1, "Anne"); w.check()
    # fork (Clone, awset_test.go:104): tensor state is a value; spec clones
    fork_spec = [s.clone() for s in w.spec]
    fork_state = w.state
    w.add(1, "Bob"); w.del_(0, "Bob")
    w.merge(1, 0); w.merge(0, 1); w.check()
    assert w.members(0) == ["Anne", "Bob"]  # writer wins
    # restore fork and run the non-concurrent variant (awset_test.go:113-121)
    w.spec, w.state = fork_spec, fork_state
    w.add(1, "Bob"); w.merge(1, 0); w.del_(0, "Bob")
    w.merge(1, 0); w.merge(0, 1); w.check()
    assert w.members(0) == ["Anne"]
    assert w.members(1) == ["Anne"]


def test_kernel_commutativity():
    """TestAWSetCommutativity (awset_test.go:124-154)."""
    w = DualWorld()
    w.add(0, "Shelly", "Bob", "Pete", "Anna")
    w.add(1, "Shelly", "Bob", "Pete", "Anna")
    w.del_(0, "Anna"); w.add(1, "Anna"); w.check()
    fork_spec = [s.clone() for s in w.spec]
    fork_state = w.state
    w.merge(1, 0); w.merge(0, 1); w.check()
    expected = ["Anna", "Bob", "Pete", "Shelly"]
    assert w.members(0) == expected and w.members(1) == expected
    w.spec, w.state = fork_spec, fork_state
    w.merge(0, 1); w.merge(1, 0); w.check()
    assert w.members(0) == expected and w.members(1) == expected


def test_kernel_stale_dot_overwrite_quirk():
    """The kernel must reproduce the unconditional dot overwrite
    (awset.go:142) including the stale-dot case that loses a concurrent
    re-add (pinned in test_spec_conformance)."""
    w = DualWorld(num_replicas=3, num_elements=8, num_actors=3)
    w.add(2, "x"); w.merge(1, 2); w.merge(0, 1)
    w.del_(2, "x"); w.add(0, "x"); w.check()
    w.merge(0, 1); w.check("stale overwrite")
    arr = awset.to_arrays(w.state)
    e = w.dictionary.encode("x")
    assert arr["dot_actor"][0][e] == 2 and arr["dot_counter"][0][e] == 1
    w.merge(0, 2); w.check("removal after stale overwrite")
    assert w.members(0) == []


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_kernel_randomized_conformance(seed):
    """Randomized op soups over 3 replicas / 3 actors: bitwise agreement
    with the spec after every single op (the strongest conformance mode)."""
    rng = random.Random(seed)
    universe = [f"k{i}" for i in range(10)]
    w = DualWorld(num_replicas=3, num_elements=12, num_actors=3)
    for step in range(120):
        p = rng.random()
        r = rng.randrange(3)
        if p < 0.45:
            w.add(r, rng.choice(universe))
        elif p < 0.7:
            w.del_(r, rng.choice(universe))
        else:
            s = rng.randrange(3)
            if s != r:
                w.merge(r, s)
        w.check(f"seed={seed} step={step}")


def test_kernel_batched_pairwise_matches_sequential():
    """merge_pairwise (vmapped) must equal R independent single merges."""
    rng = random.Random(42)
    R, E, A = 8, 16, 8
    dst = awset.init(R, E, A)
    src = awset.init(R, E, A)
    # random independent histories
    for _ in range(60):
        which = rng.random() < 0.5
        st = dst if which else src
        r, e = rng.randrange(R), rng.randrange(E)
        if rng.random() < 0.7:
            st = awset.add_element(st, np.uint32(r), np.uint32(e))
        else:
            st = awset.del_element(st, np.uint32(r), np.uint32(e))
        if which:
            dst = st
        else:
            src = st
    batched, _ = merge_ops.merge_pairwise_jit(dst, src)
    for r in range(R):
        single, _ = merge_ops.merge_one_into(dst, r, src, r)
        for name in ("vv", "present", "dot_actor", "dot_counter"):
            assert np.array_equal(
                np.asarray(getattr(batched, name)[r]),
                np.asarray(getattr(single, name)[r]),
            ), (r, name)


def test_kernel_trace_matches_spec_outcomes():
    """The decision tensors must reproduce the reference's five logOutcome
    labels (awset.go:126-156) as recorded by the spec's trace hook."""
    events = []
    A_spec = AWSet(actor=0, version_vector=VersionVector([0, 0]),
                   trace=events.append)
    B_spec = AWSet(actor=1, version_vector=VersionVector([0, 0]))
    dictionary = ElementDict(capacity=8)
    # build divergent states: shared, dst-only-seen, src-only-new, deleted...
    A_spec.add("both_same")
    B_spec.merge(A_spec)          # B now has both_same with A's dot
    A_spec.add("both_diff")       # A re-adds so dots will differ after B add
    B_spec.add("both_diff")
    A_spec.add("dst_only_unseen")
    B_spec.add("src_only_new")
    A_spec.merge(B_spec)          # A sees src_only_new
    A_spec.del_("src_only_new")   # now A's clock covers it but absent -> skip
    events.clear()
    # tensor states mirroring the spec pair
    state = awset.from_arrays(pack_awsets([A_spec, B_spec], dictionary, 2))
    dst = {k: v[0] for k, v in awset.to_arrays(state).items()}
    src = {k: v[1] for k, v in awset.to_arrays(state).items()}
    _, _, _, _, trace = merge_ops.merge_kernel(
        dst["vv"], dst["present"], dst["dot_actor"], dst["dot_counter"],
        src["vv"], src["present"], src["dot_actor"], src["dot_counter"],
        with_trace=True,
    )
    A_spec.merge(B_spec)  # spec records events
    code = {"update": merge_ops.OUTCOME_UPDATE, "keep": merge_ops.OUTCOME_KEEP,
            "skip": merge_ops.OUTCOME_SKIP, "add": merge_ops.OUTCOME_ADD,
            "remove": merge_ops.OUTCOME_REMOVE}
    p1 = np.asarray(trace.phase1)
    p2 = np.asarray(trace.phase2)
    seen_lanes_p1, seen_lanes_p2 = set(), set()
    for ev in events:
        e = dictionary.encode(ev.key)
        if ev.phase == 1:
            assert p1[e] == code[ev.outcome], (ev, p1[e])
            seen_lanes_p1.add(e)
        else:
            assert p2[e] == code[ev.outcome], (ev, p2[e])
            seen_lanes_p2.add(e)
    # lanes with no spec event must be OUTCOME_NONE
    for e in range(8):
        if e not in seen_lanes_p1:
            assert p1[e] == merge_ops.OUTCOME_NONE, e
        if e not in seen_lanes_p2:
            assert p2[e] == merge_ops.OUTCOME_NONE, e
