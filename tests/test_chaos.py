"""Socket-level chaos harness (net/faults.ChaosProxy) + the resilient
runtime driving real Nodes through it.

The tensor layer's drop masks (parallel/gossip.py) validate the merge
ALGEBRA under loss; these tests validate the WIRE STACK: framing,
deadlines, the all-or-nothing apply, breaker degradation, and
checkpoint restart — against injected drops, truncations, garbling,
duplicates, and an asymmetric partition that later heals.  Scenarios
are seeded/scripted so failures reproduce."""

import dataclasses
import socket
import time

import numpy as np
import pytest

from go_crdt_playground_tpu.net import framing
from go_crdt_playground_tpu.net.antientropy import SyncSupervisor
from go_crdt_playground_tpu.net.faults import (ChaosProxy, ChaosScenario,
                                               fleet_proxies)
from go_crdt_playground_tpu.net.peer import (Node, PeerReset, SyncError)
from go_crdt_playground_tpu.obs import Recorder
from go_crdt_playground_tpu.utils.backoff import BackoffPolicy

E = 48
FAST = BackoffPolicy(base_s=0.002, cap_s=0.02, max_retries=2, jitter=0.0)


def proxy_addr(p: ChaosProxy):
    return ("127.0.0.1", p.port)


def sync_eventually(node: Node, addr, deadline_s: float = 10.0):
    """Retry a direct sync until it lands (for post-fault assertions)."""
    deadline = time.monotonic() + deadline_s
    while True:
        try:
            return node.sync_with(addr, timeout=5.0)
        except (OSError, framing.ProtocolError, framing.RemoteError):
            if time.monotonic() > deadline:
                raise
            time.sleep(0.02)


# -- scripted single-fault behavior ----------------------------------------


def test_scripted_drop_before_hello():
    a, b = Node(0, E, 2), Node(1, E, 2)
    with b:
        proxy = ChaosProxy(b.serve(), script=["drop", "ok"])
        with proxy:
            a.add(1)
            with pytest.raises(SyncError):
                a.sync_with(proxy_addr(proxy), timeout=3.0)
            assert b.members().size == 0, "dropped dial must apply nothing"
            sync_eventually(a, proxy_addr(proxy))
            assert 1 in b.members()
            c = proxy.counters()
            assert c["dropped"] == 1 and c["passed"] == 1


def test_mid_frame_truncation_is_all_or_nothing():
    """The acceptance property: a torn PAYLOAD frame must never leave a
    partially-applied state — the server applies a frame only once it
    has ALL of it (and decode precedes apply)."""
    a, b = Node(0, E, 2), Node(1, E, 2)
    with b:
        # cut after 30 forwarded bytes: past the ~11-byte HELLO frame,
        # inside the PAYLOAD frame carrying 20 adds
        proxy = ChaosProxy(b.serve(), script=["truncate:30"])
        with proxy:
            a.add(*range(20))
            with pytest.raises(PeerReset):
                # torn frames surface as the RESET class (transport
                # loss), which the supervisor retries — classification
                # is part of the pinned behavior
                a.sync_with(proxy_addr(proxy), timeout=3.0)
            # the server saw a torn PAYLOAD: nothing may have applied
            time.sleep(0.1)  # let the server handler finish unwinding
            assert b.members().size == 0, \
                "mid-frame truncation corrupted applied state"
            assert proxy.counters()["truncated"] == 1
            # script exhausted -> clean pass-through: now it converges
            sync_eventually(a, proxy_addr(proxy))
            np.testing.assert_array_equal(b.members(), np.arange(20))


def test_garbled_magic_rejected_without_corruption():
    """A flip in the frame preamble: the server rejects before decode,
    the client sees the torn connection, nothing applies."""
    a, b = Node(0, E, 2), Node(1, E, 2)
    with b:
        proxy = ChaosProxy(b.serve(), script=["garble:0"])
        with proxy:
            a.add(3, 7)
            before = b.vv().copy()
            with pytest.raises((SyncError, framing.RemoteError)):
                a.sync_with(proxy_addr(proxy), timeout=3.0)
            time.sleep(0.1)
            assert b.members().size == 0
            np.testing.assert_array_equal(b.vv(), before), \
                "a garbled frame must not move the receiver's clock"
            assert proxy.counters()["garbled"] == 1
            sync_eventually(a, proxy_addr(proxy))
            np.testing.assert_array_equal(b.members(), [3, 7])


def test_garbled_body_field_rejected_as_remote_error():
    """A flip inside the HELLO body (the element-universe varint): the
    server's decode rejects it and reports MSG_ERROR — the client gets
    the typed RemoteError, and again nothing applies."""
    a, b = Node(0, E, 2), Node(1, E, 2)
    with b:
        # HELLO body layout: varint actor | varint E | vv-section; with
        # magic(2)+type(1)+len(1) the E varint is frame byte 5
        proxy = ChaosProxy(b.serve(), script=["garble:5"])
        with proxy:
            a.add(3)
            with pytest.raises(framing.RemoteError,
                               match="universe mismatch"):
                a.sync_with(proxy_addr(proxy), timeout=3.0)
            time.sleep(0.1)
            assert b.members().size == 0
            sync_eventually(a, proxy_addr(proxy))
            np.testing.assert_array_equal(b.members(), [3])


def test_duplicate_delivery_is_idempotent():
    """The proxy records the client→server bytes and replays them on a
    fresh connection: the same PAYLOAD applied twice — on the real wire
    bytes — must be a no-op the second time (SURVEY §5.3 idempotence)."""
    rec = Recorder()
    a, b = Node(0, E, 2), Node(1, E, 2, recorder=rec)
    with b:
        proxy = ChaosProxy(b.serve(), script=["duplicate"])
        with proxy:
            a.add(1, 2, 3)
            a.sync_with(proxy_addr(proxy), timeout=5.0)
            members_after = set(b.members())
            vv_after = b.vv().copy()
            # wait for the ghost replay to hit the server
            deadline = time.monotonic() + 10.0
            while (rec.snapshot()["counters"].get("sync.exchanges", 0) < 2
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            assert rec.snapshot()["counters"]["sync.exchanges"] == 2, \
                "the duplicate delivery never reached the server"
            assert set(b.members()) == members_after == {1, 2, 3}
            np.testing.assert_array_equal(b.vv(), vv_after), \
                "duplicate apply must not advance the clock"
            assert proxy.counters()["duplicated"] == 1


def test_seeded_scenario_rates_are_deterministic():
    """Two proxies with the same seed and scenario plan identical fault
    sequences (the determinism contract chaos runs replay on)."""
    srv = socket.create_server(("127.0.0.1", 0))
    try:
        sc = ChaosScenario(drop_rate=0.3, truncate_rate=0.2,
                           duplicate_rate=0.2)
        plans = []
        for _ in range(2):
            p = ChaosProxy(srv.getsockname()[:2], seed=99,
                           scenario=dataclasses.replace(sc))
            seq = [p._next_plan() for _ in range(40)]
            plans.append([(pl.action, pl.cut_after, pl.duplicate)
                          for pl in seq])
            p.close()
        assert plans[0] == plans[1]
        acts = [a for a, _, _ in plans[0]]
        assert "drop" in acts and "truncate" in acts, \
            "at 30%/20% rates over 40 draws both faults must appear"
    finally:
        srv.close()


def test_partition_refuses_then_heals():
    a, b = Node(0, E, 2), Node(1, E, 2)
    with b:
        proxy = ChaosProxy(b.serve())
        with proxy:
            a.add(5)
            proxy.partition()
            with pytest.raises(SyncError):
                a.sync_with(proxy_addr(proxy), timeout=3.0)
            assert proxy.counters()["refused"] == 1
            assert b.members().size == 0
            proxy.heal()
            sync_eventually(a, proxy_addr(proxy))
            assert 5 in b.members()


# -- the acceptance scenario ------------------------------------------------


def test_seeded_chaos_fleet_acceptance(tmp_path):
    """ISSUE acceptance: a seeded chaos scenario — ≥20% exchange drop,
    one asymmetric partition that later heals, one guaranteed mid-frame
    truncation — reaches full membership convergence across a ≥4-node
    fleet, with breaker open/half-open/close transitions and
    per-failure-class retry counts visible in Recorder.snapshot(), and a
    killed-and-restored node (checkpoint restart) reconverging via the
    FULL-state first-contact branch."""
    from go_crdt_playground_tpu.net.framing import MODE_FULL

    N_ACTIVE, N_ACTORS = 4, 5     # actor 4 joins late (FULL-path proof)
    recs = [Recorder() for _ in range(N_ACTIVE)]
    # a short server-side HELLO deadline keeps torn exchanges cheap so
    # the chaos rounds stay fast (the client side inherits it too)
    nodes = [Node(i, E, N_ACTORS, recorder=recs[i], hello_timeout_s=0.5)
             for i in range(N_ACTIVE)]
    proxies = []
    sups = []
    ck = str(tmp_path / "node3.ckpt")
    try:
        addrs = [n.serve() for n in nodes]
        for i, n in enumerate(nodes):
            n.add(*range(i * 8, i * 8 + 8))
        scenario = ChaosScenario(drop_rate=0.25, truncate_rate=0.1,
                                 duplicate_rate=0.1)
        proxies = fleet_proxies(addrs, seed=17, scenario=scenario)
        # one mid-frame truncation is GUARANTEED (not left to the rates):
        # node 1's first inbound exchange tears inside the PAYLOAD frame
        proxies[1]._script.append("truncate:30")
        for i in range(N_ACTIVE):
            peer_addrs = [proxy_addr(proxies[j])
                          for j in range(N_ACTIVE) if j != i]
            sups.append(SyncSupervisor(
                nodes[i], peer_addrs, policy=FAST, sync_timeout_s=2.0,
                breaker_threshold=2, breaker_cooldown_s=0.1,
                interval_s=0.0, recorder=recs[i], seed=700 + i,
                checkpoint_path=ck if i == 3 else None,
                checkpoint_every=2 if i == 3 else 0))

        def lockstep():
            for s in sups:
                s.sync_round()

        expected = set(range(N_ACTIVE * 8))

        def converged(members_expected, live_nodes):
            vv0 = live_nodes[0].vv()
            return all(set(n.members()) == members_expected
                       and np.array_equal(n.vv(), vv0)
                       for n in live_nodes)

        # round 0 under loss, then partition node 0's inbound for three
        # rounds (asymmetric: node 0 still dials OUT), then heal
        lockstep()
        proxies[0].partition()
        for _ in range(3):
            lockstep()
            time.sleep(0.11)  # let breaker cooldowns elapse between rounds
        proxies[0].heal()
        deadline = time.monotonic() + 90.0
        while not converged(expected, nodes):
            assert time.monotonic() < deadline, (
                "fleet failed to converge under chaos: " +
                str([sorted(n.members()) for n in nodes]))
            lockstep()
            time.sleep(0.05)

        # the chaos actually fired
        census = {}
        for p in proxies:
            for k, v in p.counters().items():
                census[k] = census.get(k, 0) + v
        assert census["refused"] >= 1, "partition never refused a dial"
        assert census["truncated"] >= 1, "no mid-frame truncation fired"
        assert census["dropped"] >= 1, "25% drop rate never dropped"

        # drain: the fleet can converge transitively before any OPEN
        # breaker's half-open probe has fired — keep gossiping (the
        # merge is idempotent; a converged fleet stays converged) until
        # every breaker worked back to CLOSED, which is itself part of
        # the acceptance story (open -> half-open -> closed visible)
        def agg_counters():
            out = {}
            for r in recs:
                for k, v in r.snapshot()["counters"].items():
                    out[k] = out.get(k, 0) + v
            return out

        deadline = time.monotonic() + 60.0
        while not all(
                s.breaker(p).state == "closed"
                for s in sups for p in s.peers):
            assert time.monotonic() < deadline, \
                "breakers never recovered after the heal"
            lockstep()
            time.sleep(0.11)

        # degradation is visible in the recorders: breaker transitions
        # and per-failure-class retry counts
        agg = agg_counters()
        assert agg.get("breaker.to_open", 0) >= 1, agg
        assert agg.get("breaker.to_half_open", 0) >= 1, agg
        assert agg.get("breaker.to_closed", 0) >= 1, agg
        retry_classes = {k.split("sync.retries.")[1]: v
                         for k, v in agg.items()
                         if k.startswith("sync.retries.")}
        assert retry_classes and all(v >= 1
                                     for v in retry_classes.values()), agg
        assert agg.get("sync.checkpoints", 0) >= 1, \
            "node 3's supervisor never checkpointed"

        # -- crash: kill node 3, fleet moves on ---------------------------
        sups[3].stop(timeout=2.0)
        nodes[3].close()
        proxies[3].close()
        nodes[0].add(40, 41)
        for _ in range(2):
            for s in sups[:3]:
                s.sync_round()

        # -- recovery: restore node 3 from its supervisor checkpoint ------
        rec3 = Recorder()
        sup3 = SyncSupervisor.restore(
            ck, [proxy_addr(proxies[j]) for j in range(3)],
            recorder=rec3, policy=FAST, sync_timeout_s=5.0,
            interval_s=0.0, seed=703)
        restored = sup3.node
        assert restored.actor == 3
        assert set(restored.members()) <= expected, \
            "checkpoint must predate the kill"

        # FULL-state first-contact branch: a late joiner (actor 4) that
        # never exchanged with actor 3 — the restored node's first
        # exchange toward it must ship FULL state
        late = Node(4, E, N_ACTORS)
        with late:
            addr4 = late.serve()
            late.add(44, 45)
            restored.serve()
            stats = restored.sync_with(addr4, timeout=5.0)
            assert stats.mode_sent == MODE_FULL, \
                "restored replica's first contact must ride FULL state"

            # reconverge the whole (now 5-member) fleet; the survivors
            # still sit behind their chaos proxies
            expected2 = expected | {40, 41, 44, 45}
            live = [nodes[0], nodes[1], nodes[2], restored, late]
            deadline = time.monotonic() + 90.0
            while not converged(expected2, live):
                assert time.monotonic() < deadline, (
                    "fleet failed to reconverge after restart: " +
                    str([sorted(n.members()) for n in live]))
                for s in sups[:3]:
                    s.sync_round()
                sup3.sync_round()
                try:
                    restored.sync_with(addr4, timeout=5.0)
                except (OSError, framing.ProtocolError):
                    pass
                time.sleep(0.05)
    finally:
        for s in sups:
            s.stop(timeout=1.0)
        for p in proxies:
            p.close()
        for n in nodes:
            n.close()


# -- the long soak, CI-sized ------------------------------------------------


@pytest.mark.slow
def test_chaos_soak_quick_mode(tmp_path):
    """tools/chaos_soak.py --quick must complete, converge at every
    severity, and write a well-formed curve artifact.  slow-marked: the
    tier-1 gate never pays for the soak."""
    import json
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))
    import chaos_soak

    out = str(tmp_path / "CHAOS_CURVE.json")
    rc = chaos_soak.main(["--quick", "--out", out])
    assert rc == 0
    artifact = json.loads(Path(out).read_text())
    assert artifact["curve"], "empty curve"
    faulted = [e for e in artifact["curve"] if e["drop_rate"] > 0]
    assert faulted and all(
        e["faults_injected"]["dropped"] + e["faults_injected"]["truncated"]
        > 0 for e in faulted), "quick soak injected no faults"
    assert all(e["converged_runs"] == e["seeds"]
               for e in artifact["curve"])
