"""Elastic recovery (SURVEY §5.3-5.4): crash a networked replica,
restore it from a checkpoint, and let anti-entropy self-heal the gap —
state-based merge is idempotent and commutative-on-membership, so a
node rejoining with stale state converges like any other exchange."""

import numpy as np

from go_crdt_playground_tpu.models.spec import AWSetDelta, VersionVector
from go_crdt_playground_tpu.net import Node

E, A = 16, 3


def _spec_world():
    return [AWSetDelta(actor=i, version_vector=VersionVector([0] * A),
                       delta_semantics="v2") for i in range(A)]


def _sync(nodes, specs, dst, src, addr):
    nodes[dst].sync_with(addr)
    # push-pull: server (src) absorbs client's payload, then client
    # absorbs server's
    specs[src].merge(specs[dst])
    specs[dst].merge(specs[src])


def _check(nodes, specs):
    for n, s in zip(nodes, specs):
        if n is None:
            continue
        want = sorted(int(k[1:]) for k in s.entries)
        np.testing.assert_array_equal(n.members(), want)


def test_crash_restore_resync(tmp_path):
    specs = _spec_world()
    nodes = [Node(i, E, A) for i in range(A)]
    addrs = [n.serve() for n in nodes]
    try:
        # phase 1: divergent writes + partial sync
        nodes[0].add(1, 2)
        specs[0].add("e1", "e2")
        nodes[1].add(3)
        specs[1].add("e3")
        nodes[2].add(4, 5)
        specs[2].add("e4", "e5")
        _sync(nodes, specs, 0, 1, addrs[1])
        _sync(nodes, specs, 2, 0, addrs[0])
        _check(nodes, specs)

        # phase 2: checkpoint node 1, then crash it
        ck = str(tmp_path / "node1.ckpt")
        nodes[1].save(ck, metadata={"round": 2})
        nodes[1].add(6)          # post-checkpoint write, LOST in the crash
        nodes[1].close()
        nodes[1] = None

        # the lost write never happened in the surviving world
        # (spec world models only what the cluster can still learn)
        # phase 3: the world moves on without node 1
        nodes[0].delete(2)
        specs[0].del_("e2")
        nodes[2].add(7)
        specs[2].add("e7")
        _sync(nodes, specs, 0, 2, addrs[2])

        # phase 4: restore node 1 from the checkpoint and rejoin
        nodes[1] = Node.restore(ck)
        assert nodes[1].actor == 1
        addrs[1] = nodes[1].serve()
        # its state is the pre-crash checkpoint: e1..e3 seen, e6 gone
        np.testing.assert_array_equal(nodes[1].members(), [1, 2, 3])

        # full mesh of exchanges heals everyone
        for dst, src in ((1, 0), (0, 1), (1, 2), (2, 1), (0, 2)):
            _sync(nodes, specs, dst, src, addrs[src])
        _check(nodes, specs)
        # all replicas agree (membership + clocks, v2 joins clocks)
        m0, m1, m2 = (nodes[i].members() for i in range(A))
        np.testing.assert_array_equal(m0, m1)
        np.testing.assert_array_equal(m1, m2)
        np.testing.assert_array_equal(nodes[0].vv(), nodes[1].vv())
        np.testing.assert_array_equal(nodes[1].vv(), nodes[2].vv())
    finally:
        for n in nodes:
            if n is not None:
                n.close()


def test_restore_preserves_semantics_switches(tmp_path):
    n = Node(0, E, A, delta_semantics="reference",
             strict_reference_semantics=False)
    n.add(3)
    path = n.save(str(tmp_path / "n.ckpt"))
    n.close()
    back = Node.restore(path)
    try:
        assert back.delta_semantics == "reference"
        assert back.strict_reference_semantics is False
        np.testing.assert_array_equal(back.members(), [3])
    finally:
        back.close()
