"""Conformance gate for the fused Pallas gossip kernel.

ops/pallas_merge.py must be bitwise-identical to the XLA kernel
(ops/merge.py) — which tests/test_merge_kernel.py already pins to the
executable spec — so equality here transitively pins the Pallas kernel
to the reference semantics (awset.go:107-161).

On the CPU test mesh the kernel runs in Pallas interpreter mode (the
wrapper auto-selects it off-TPU); the same code path compiles on real
TPU, where it was validated bitwise-equal at R=10K, E=A=256.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from go_crdt_playground_tpu.models.awset import AWSetState
from go_crdt_playground_tpu.ops import merge as merge_ops
from go_crdt_playground_tpu.ops import pallas_merge
from go_crdt_playground_tpu.parallel import gossip

FIELDS = ("vv", "present", "dot_actor", "dot_counter")


def rand_state(rng, num_r, num_e, num_a, max_counter=7):
    present = rng.random((num_r, num_e)) < 0.5
    da = rng.integers(0, num_a, (num_r, num_e), dtype=np.uint32)
    dc = rng.integers(1, max_counter, (num_r, num_e), dtype=np.uint32)
    vv = rng.integers(0, max_counter + 2, (num_r, num_a), dtype=np.uint32)
    da = np.where(present, da, 0)
    dc = np.where(present, dc, 0)
    return AWSetState(
        vv=jnp.asarray(vv), present=jnp.asarray(present),
        dot_actor=jnp.asarray(da), dot_counter=jnp.asarray(dc),
        actor=jnp.zeros((num_r,), jnp.uint32))


def assert_states_equal(want, got):
    for name in FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(want, name)), np.asarray(getattr(got, name)),
            err_msg=name)


@pytest.mark.parametrize(
    "num_r,num_e,num_a",
    [
        (8, 16, 2),      # reference-shaped world (2 actors)
        (7, 300, 5),     # pad path: E, A not lane multiples; odd R
        (16, 256, 64),   # lane-aligned
        (5, 640, 3),     # multiple E tiles (block_e=512 -> grid j > 1)
    ],
)
def test_fused_round_matches_xla_kernel(num_r, num_e, num_a):
    rng = np.random.default_rng(42)
    state = rand_state(rng, num_r, num_e, num_a)
    for offset in (1, 2):
        perm = gossip.ring_perm(num_r, offset)
        want = gossip.gossip_round(state, perm)
        got = pallas_merge.pallas_gossip_round(state, perm)
        assert_states_equal(want, got)
        state = want  # iterate: round 2 runs on merged state


def test_fused_round_arbitrary_permutation():
    rng = np.random.default_rng(7)
    state = rand_state(rng, 12, 128, 4)
    perm = jnp.asarray(rng.permutation(12).astype(np.uint32))
    want = gossip.gossip_round(state, perm)
    got = pallas_merge.pallas_gossip_round(state, perm)
    assert_states_equal(want, got)


def test_fused_round_large_counters_exact():
    """The hi/lo MXU split must be exact up to full uint32 range."""
    rng = np.random.default_rng(3)
    state = rand_state(rng, 6, 128, 3)
    big = np.asarray(state.vv, dtype=np.uint64)
    vv = jnp.asarray(((big * 97003) + 0xFFFF0000) % (1 << 32),
                     dtype=jnp.uint32)
    dc = jnp.where(state.present,
                   jnp.asarray(rng.integers(0xFFFE0000, 0xFFFFFFFF,
                                            state.dot_counter.shape,
                                            dtype=np.uint32)), 0)
    state = state._replace(vv=vv, dot_counter=dc)
    perm = gossip.ring_perm(6, 1)
    want = gossip.gossip_round(state, perm)
    got = pallas_merge.pallas_gossip_round(state, perm)
    assert_states_equal(want, got)


def test_pairwise_matches_xla_kernel():
    rng = np.random.default_rng(11)
    dst = rand_state(rng, 6, 200, 3)
    src = rand_state(rng, 6, 200, 3)
    want, _ = merge_ops.merge_pairwise(dst, src)
    got = pallas_merge.pallas_merge_pairwise(dst, src)
    assert_states_equal(want, got)


@pytest.mark.parametrize(
    "num_r,num_e,num_a",
    [
        (8, 16, 2),      # reference-shaped world
        (7, 300, 5),     # row/lane padding: R not a sublane multiple
        (12, 640, 64),   # multiple E tiles, R pads to 16
    ],
)
def test_multirow_kernel_matches_xla(num_r, num_e, num_a):
    """The production multi-row kernel (block-diagonal MXU HasDot)
    against the XLA round, including the ragged padding paths."""
    rng = np.random.default_rng(23)
    state = rand_state(rng, num_r, num_e, num_a)
    for offset in (1, 3):
        perm = gossip.ring_perm(num_r, offset)
        want = gossip.gossip_round(state, perm, kernel="xla")
        got = pallas_merge.pallas_gossip_round_rows(state, perm)
        assert_states_equal(want, got)
        state = want


def test_multirow_kernel_large_counters_exact():
    rng = np.random.default_rng(31)
    state = rand_state(rng, 9, 128, 3)
    big = np.asarray(state.vv, dtype=np.uint64)
    vv = jnp.asarray(((big * 97003) + 0xFFFF0000) % (1 << 32),
                     dtype=jnp.uint32)
    dc = jnp.where(state.present,
                   jnp.asarray(rng.integers(0xFFFE0000, 0xFFFFFFFF,
                                            state.dot_counter.shape,
                                            dtype=np.uint32)), 0)
    state = state._replace(vv=vv, dot_counter=dc)
    perm = gossip.ring_perm(9, 1)
    want = gossip.gossip_round(state, perm, kernel="xla")
    got = pallas_merge.pallas_gossip_round_rows(state, perm)
    assert_states_equal(want, got)


def test_gossip_round_kernel_dispatch_equal():
    """kernel="pallas" (interpreter off-TPU) == kernel="xla" through the
    public gossip_round entry point, drop-mask included."""
    rng = np.random.default_rng(37)
    state = rand_state(rng, 8, 64, 4)
    perm = gossip.ring_perm(8, 2)
    drop = jnp.asarray(rng.random(8) < 0.4)
    want = gossip.gossip_round(state, perm, drop, kernel="xla")
    got = gossip.gossip_round(state, perm, drop, kernel="pallas")
    assert_states_equal(want, got)


@pytest.mark.parametrize("offset", [0, 1, 63, 64, 65, 127, 500])
def test_ring_round_matches_xla(offset):
    """Ring-fused kernel (in-place partner windows via block index maps
    + dynamic sublane roll) vs the XLA round over the same ring perm,
    across block-aligned and misaligned offsets incl. the wraparound."""
    rng = np.random.default_rng(7)
    num_r = 8 * pallas_merge._BLOCK_R  # ring path needs aligned blocks
    state = rand_state(rng, num_r, 256, 5)
    want = gossip.gossip_round(state, gossip.ring_perm(num_r, offset))
    got = pallas_merge.pallas_ring_round_rows(state, offset)
    assert_states_equal(want, got)


def test_ring_round_fallback_unaligned_rows():
    """R not a _BLOCK_R multiple falls back to the gather path with
    identical results."""
    rng = np.random.default_rng(8)
    state = rand_state(rng, 70, 128, 3)
    want = gossip.gossip_round(state, gossip.ring_perm(70, 9))
    got = pallas_merge.pallas_ring_round_rows(state, 9)
    assert_states_equal(want, got)


def test_ring_round_traced_offset_one_program():
    """The offset is data: a lax.scan over different offsets reuses one
    compiled ring program and matches the per-offset XLA rounds."""
    import jax

    rng = np.random.default_rng(9)
    num_r = 4 * pallas_merge._BLOCK_R
    state = rand_state(rng, num_r, 128, 4)
    offsets = jnp.asarray([1, 64, 65, 200], jnp.uint32)

    @jax.jit
    def run(s):
        def body(c, off):
            return pallas_merge.pallas_ring_round_rows(c, off), None
        return jax.lax.scan(body, s, offsets)[0]

    want = state
    for off in [1, 64, 65, 200]:
        want = gossip.gossip_round(want, gossip.ring_perm(num_r, off))
    assert_states_equal(want, run(state))


def test_ring_gossip_round_dispatch_equal():
    """parallel.gossip.ring_gossip_round: every kernel choice and the
    drop-mask lane agree bitwise with the perm-based round."""
    rng = np.random.default_rng(10)
    num_r = 2 * pallas_merge._BLOCK_R
    state = rand_state(rng, num_r, 128, 4)
    drop = jnp.asarray(rng.random(num_r) < 0.3)
    want = gossip.gossip_round(state, gossip.ring_perm(num_r, 3), drop)
    for kernel in ("xla", "pallas"):
        got = gossip.ring_gossip_round(state, 3, drop, kernel=kernel)
        assert_states_equal(want, got)
