"""Driver-contract tests for __graft_entry__.

Round 1 postmortem: the two driver entry points (entry, dryrun_multichip)
were the only significant code paths with zero test coverage, and
dryrun_multichip deadlocked in the driver (MULTICHIP_r01 rc=124) on a
TPU-backend init reached through module imports that preceded the platform
override.  These tests run both entry points in fresh subprocesses with
hard timeouts, exactly as the driver would, so a regression of that class
fails CI instead of losing a round.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from __graft_entry__ import _scrubbed_cpu_env  # noqa: E402

ENTRY_SNIPPET = """
import jax
from __graft_entry__ import entry
fn, args = entry()
out = jax.jit(fn)(*args)
jax.block_until_ready(out)
merged, converged = out
assert merged.present.shape == (256, 256)
assert converged.shape == ()
print("ENTRY_OK", jax.devices()[0].platform)
"""


def test_entry_forward_step_compiles_and_runs():
    """entry() must produce a jittable fn + example args that execute."""
    proc = subprocess.run(
        [sys.executable, "-c", ENTRY_SNIPPET],
        env=_scrubbed_cpu_env(1), cwd=REPO, timeout=300,
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "ENTRY_OK cpu" in proc.stdout


def test_dryrun_multichip_8_devices():
    """dryrun_multichip(8) must finish (it owns its subprocess + timeout)
    with EVERY sharded path converged; called from a process where the
    ambient env still points at the TPU tunnel — the exact condition
    that hung round 1."""
    proc = subprocess.run(
        [sys.executable, "-c",
         "from __graft_entry__ import dryrun_multichip; dryrun_multichip(8)"],
        env=dict(os.environ), cwd=REPO, timeout=660,
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "dryrun_multichip ok: 5/5 sharded paths converged" in proc.stdout
    assert "converged=False" not in proc.stdout


def test_dryrun_multichip_odd_device_count():
    """The (n, 1) mesh fallback path for non-even device counts."""
    proc = subprocess.run(
        [sys.executable, "-c",
         "from __graft_entry__ import dryrun_multichip; dryrun_multichip(3)"],
        env=dict(os.environ), cwd=REPO, timeout=660,
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "delta-default(3, 1)" in proc.stdout
    assert "5/5 sharded paths converged" in proc.stdout


def test_entry_shape_triggers_fused_dispatch():
    """The driver probe must exercise the production kernel: entry()'s
    example shape satisfies every condition of ring_gossip_round's
    pallas auto-dispatch (single-device TPU picks the ring-fused path)."""
    from __graft_entry__ import entry
    from go_crdt_playground_tpu.ops.pallas_merge import (
        MAX_FUSED_ACTORS, ring_supported)

    _, (state, offset) = entry()
    assert ring_supported(state.present.shape[0])
    assert state.vv.shape[-1] <= MAX_FUSED_ACTORS
    assert int(offset) < state.present.shape[0]
