"""Opt-in on-TPU smoke tests: Mosaic compile + bitwise proof for every
fused kernel, on the real chip.

CI runs the suite on the virtual CPU mesh where Pallas kernels execute
in interpreter mode — a Mosaic *compile* regression (an op the TPU
backend can't legalize, a layout the compiler crashes on) would
otherwise first surface in the driver's bench run.  These tests run the
real lowering:

    CRDT_TPU_TEST_PLATFORM=axon python -m pytest tests/test_tpu_smoke.py

(tests/conftest.py pins the suite to CPU unless that env var opts in;
the whole module skips when the ambient backend isn't a TPU.)
"""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

if jax.default_backend() != "tpu":
    pytest.skip("opt-in TPU smoke: set CRDT_TPU_TEST_PLATFORM=axon "
                "(real-chip Mosaic compile proof; CPU CI runs the "
                "interpret-mode suites instead)",
                allow_module_level=True)

import jax.numpy as jnp  # noqa: E402

from go_crdt_playground_tpu.models import awset_delta  # noqa: E402
from go_crdt_playground_tpu.ops import pallas_delta  # noqa: E402
from go_crdt_playground_tpu.ops import pallas_merge  # noqa: E402
from go_crdt_playground_tpu.parallel import gossip  # noqa: E402

R = 2 * pallas_merge._BLOCK_R
E, A = 256, 256


def _merge_state_wide(seed, num_e):
    rng = np.random.default_rng(seed)
    present = rng.random((R, num_e)) < 0.5
    da = np.where(present, rng.integers(0, A, (R, num_e)),
                  0).astype(np.uint32)
    dc = np.where(present, rng.integers(1, 9, (R, num_e)),
                  0).astype(np.uint32)
    from go_crdt_playground_tpu.models.awset import AWSetState

    return AWSetState(
        vv=jnp.asarray(rng.integers(0, 10, (R, A)).astype(np.uint32)),
        present=jnp.asarray(present), dot_actor=jnp.asarray(da),
        dot_counter=jnp.asarray(dc),
        actor=jnp.arange(R, dtype=jnp.uint32) % A)


def _merge_state(seed=0):
    return _merge_state_wide(seed, E)


def _delta_state(seed=1, num_e=E):
    base = _merge_state_wide(seed, num_e)
    rng = np.random.default_rng(seed + 100)
    deleted = rng.random((R, num_e)) < 0.1
    dda = np.where(deleted, rng.integers(0, A, (R, num_e)),
                   0).astype(np.uint32)
    ddc = np.where(deleted, rng.integers(0, 5, (R, num_e)),
                   0).astype(np.uint32)
    return awset_delta.AWSetDeltaState(
        vv=base.vv, present=base.present, dot_actor=base.dot_actor,
        dot_counter=base.dot_counter, actor=base.actor,
        deleted=jnp.asarray(deleted), del_dot_actor=jnp.asarray(dda),
        del_dot_counter=jnp.asarray(ddc), processed=base.vv)


def _assert_equal(want, got):
    for name in want._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(want, name)),
            np.asarray(getattr(got, name)), err_msg=name)


@pytest.mark.parametrize("offset", [1, 65])
def test_ring_merge_kernel_mosaic(offset):
    state = _merge_state()
    want = gossip.gossip_round(state, gossip.ring_perm(R, offset),
                               kernel="xla")
    got = pallas_merge.pallas_ring_round_rows(state, offset,
                                              interpret=False)
    _assert_equal(want, got)


def test_rows_merge_kernel_mosaic():
    state = _merge_state(2)
    perm = gossip.random_perm(jax.random.key(0), R)
    want = gossip.gossip_round(state, perm, kernel="xla")
    got = pallas_merge.pallas_gossip_round_rows(state, perm,
                                                interpret=False)
    _assert_equal(want, got)


def test_onerow_merge_kernel_mosaic():
    state = _merge_state(3)
    perm = gossip.ring_perm(R, 3)
    want = gossip.gossip_round(state, perm, kernel="xla")
    got = pallas_merge.pallas_gossip_round(state, perm, interpret=False)
    _assert_equal(want, got)


@pytest.mark.parametrize("offset", [1, 65])
def test_ring_delta_kernel_mosaic(offset):
    state = _delta_state()
    want = gossip.delta_gossip_round(
        state, gossip.ring_perm(R, offset), delta_semantics="v2",
        kernel="xla")
    got = pallas_delta.pallas_delta_ring_round(state, offset,
                                               interpret=False)
    _assert_equal(want, got)


@pytest.mark.parametrize("offset", [1, 65])
def test_ring_delta_kernel_strict_reference_mosaic(offset):
    """The fused STRICT-REFERENCE δ path (empty-δ VV-skip as a scratch-
    accumulated cross-E reduction, _strict_vv_epilogue) must Mosaic-
    compile — interpret-mode CI cannot prove the scratch/when lowering."""
    state = _delta_state(5)
    want = gossip.delta_gossip_round(
        state, gossip.ring_perm(R, offset), delta_semantics="reference",
        strict_reference_semantics=True, kernel="xla")
    got = pallas_delta.pallas_delta_ring_round(
        state, offset, delta_semantics="reference",
        strict_reference_semantics=True, interpret=False)
    _assert_equal(want, got)


def test_rows_delta_kernel_mosaic():
    state = _delta_state(4)
    perm = gossip.random_perm(jax.random.key(1), R)
    want = gossip.delta_gossip_round(state, perm, delta_semantics="v2",
                                     kernel="xla")
    got = pallas_delta.pallas_delta_gossip_round(state, perm,
                                                 interpret=False)
    _assert_equal(want, got)


def test_entry_runs_fused_path_on_tpu():
    """The driver's forward-step probe exercises the production kernel."""
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from __graft_entry__ import entry

    fn, args = entry()
    out, conv = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    assert conv.shape == ()


@pytest.mark.parametrize("offset", [1, 65])
def test_packed_ring_kernels_mosaic(offset):
    """Bitpacked membership kernels compile under Mosaic and agree with
    the bool layout through pack/unpack."""
    from go_crdt_playground_tpu.models import packed as packed_mod

    state = _merge_state(7)
    want = pallas_merge.pallas_ring_round_rows(state, offset,
                                               interpret=False)
    got = packed_mod.unpack_awset(
        pallas_merge.pallas_ring_round_rows_packed(
            packed_mod.pack_awset(state), offset, interpret=False), E)
    _assert_equal(want, got)

    dstate = _delta_state(8)
    dwant = pallas_delta.pallas_delta_ring_round(dstate, offset,
                                                 interpret=False)
    dgot = packed_mod.unpack_awset_delta(
        pallas_delta.pallas_delta_ring_round_packed(
            packed_mod.pack_awset_delta(dstate), offset,
            interpret=False), E)
    _assert_equal(dwant, dgot)


@pytest.mark.parametrize("num_e", [8192, 4100])
def test_packed_word_tiling_mosaic(num_e):
    """The word-tiled packed grid beyond the old E<=4096 cap (two+ lane
    groups of words; pallas_merge._packed_tiling) must Mosaic-compile
    and agree with the bool layout on the real chip — interpret-mode CI
    cannot prove the lowering."""
    from go_crdt_playground_tpu.models import packed as packed_mod

    state = _merge_state_wide(11, num_e)
    for offset in (3, 64):
        want = pallas_merge.pallas_ring_round_rows(state, offset,
                                                   interpret=False)
        got = packed_mod.unpack_awset(
            pallas_merge.pallas_ring_round_rows_packed(
                packed_mod.pack_awset(state), offset,
                interpret=False), num_e)
        _assert_equal(want, got)


def test_ormap_ring_round_mosaic():
    """OR-Map ring round (ring-fused core + LWW row gather) on-chip."""
    from go_crdt_playground_tpu.ops import lattices as L

    st = L.ormap_init(R, 64, R)
    st = L.ormap_put(st, jnp.uint32(1), jnp.uint32(3), jnp.uint32(7),
                     jnp.uint32(1))
    st = L.ormap_put(st, jnp.uint32(2), jnp.uint32(5), jnp.uint32(9),
                     jnp.uint32(2))
    want = gossip.ormap_gossip_round(st, gossip.ring_perm(R, 3),
                                     kernel="xla")
    got = gossip.ormap_ring_gossip_round(st, 3)
    _assert_equal(want, got)


def test_butterfly_shardmap_single_chip_mosaic():
    """butterfly_round_shardmap's per-shard fused kernel under shard_map
    must Mosaic-compile on the real chip.  On one device every XOR stage
    is block-local (blk = R), so this proves the local-stage lowering —
    the device-swap stages are pure ppermute + the pairwise kernel
    already proven by the ring smoke."""
    from go_crdt_playground_tpu.parallel import mesh as mesh_mod

    state = _merge_state(9)
    m = mesh_mod.make_mesh((1, 1))
    sharded = mesh_mod.shard_state(state, m)
    for stage in (0, 6):
        want = gossip.gossip_round(
            state, gossip.butterfly_perm(R, stage), kernel="xla")
        got = gossip.butterfly_round_shardmap(sharded, m, stage,
                                              kernel="pallas")
        _assert_equal(want, got)


@pytest.mark.parametrize("offset", [1, 65])
def test_dotpacked_ring_kernel_mosaic(offset):
    """The dot-word ring kernel (shift/mask unpack of (actor, counter)
    from one uint32, ~1.6x less HBM than the bool layout) must
    Mosaic-compile and agree with the bool layout on the real chip."""
    from go_crdt_playground_tpu.models import packed as packed_mod

    state = _merge_state(13)
    want = pallas_merge.pallas_ring_round_rows(state, offset,
                                               interpret=False)
    got = packed_mod.unpack_awset_dots(
        pallas_merge.pallas_ring_round_rows_dotpacked(
            packed_mod.pack_awset_dots(state), offset,
            interpret=False), E)
    _assert_equal(want, got)


@pytest.mark.parametrize("kind", ["packed", "dots"])
def test_delta_word_tiling_mosaic(kind):
    """The word-tiled δ grids beyond E=4096 must Mosaic-compile and
    agree with the bool layout on-chip.  The packed (non-dot-word) form
    carries FOUR unpacked uint32 E-arrays and is the largest
    windowed-form VMEM demand of any kernel (_RING_VMEM_LIMIT's
    sizing case); offset 3 exercises that windowed form, 64 the
    aligned one."""
    from go_crdt_playground_tpu.models import packed as packed_mod

    num_e = 8192
    state = _delta_state(21, num_e)
    for offset in (3, 64):
        want = pallas_delta.pallas_delta_ring_round(state, offset,
                                                    interpret=False)
        if kind == "packed":
            got = packed_mod.unpack_awset_delta(
                pallas_delta.pallas_delta_ring_round_packed(
                    packed_mod.pack_awset_delta(state), offset,
                    interpret=False), num_e)
        else:
            got = packed_mod.unpack_awset_delta_dots(
                pallas_delta.pallas_delta_ring_round_dotpacked(
                    packed_mod.pack_awset_delta_dots(state), offset,
                    interpret=False), num_e)
        _assert_equal(want, got)


@pytest.mark.parametrize("offset", [1, 65])
def test_dotpacked_delta_ring_kernel_mosaic(offset):
    """The δ dot-word ring kernel (both dot pairs shift/mask-unpacked
    from single uint32 words — the north-star schedule's ~1.6x HBM cut)
    must Mosaic-compile and agree with the bool layout on-chip."""
    from go_crdt_playground_tpu.models import packed as packed_mod

    state = _delta_state(17)
    want = pallas_delta.pallas_delta_ring_round(state, offset,
                                                interpret=False)
    got = packed_mod.unpack_awset_delta_dots(
        pallas_delta.pallas_delta_ring_round_dotpacked(
            packed_mod.pack_awset_delta_dots(state), offset,
            interpret=False), E)
    _assert_equal(want, got)


def test_fused_ingest_kernel_mosaic():
    """The fused ingest+δ kernel (serve hot path, ISSUE 8) must Mosaic-
    compile and agree bitwise with the XLA fused pass on-chip — the
    compile proof BENCH_INGEST.json's on-chip regeneration (ROADMAP
    item b) rides on."""
    from go_crdt_playground_tpu.ops import ingest as ingest_ops
    from go_crdt_playground_tpu.ops import pallas_ingest

    row = jax.tree.map(lambda x: x[0], _delta_state(19))
    rng = np.random.default_rng(19)
    add = jnp.asarray(rng.random((4, E)) < 0.2)
    dl = jnp.asarray(rng.random((4, E)) < 0.1)
    live = jnp.ones(4, bool)
    want = ingest_ops.ingest_rows_delta(row, add, dl, live,
                                        k_changed=16, k_deleted=16)
    got = pallas_ingest.pallas_ingest_rows_delta(
        row, add, dl, live, k_changed=16, k_deleted=16, interpret=False)
    for w, g, label in zip(want, got, ("state", "payload", "compact")):
        for name in w._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(w, name)),
                np.asarray(getattr(g, name)), err_msg=f"{label}:{name}")


def test_digest_kernel_mosaic():
    """The per-lane digest kernel (digest-sync summary path, ISSUE 9)
    must Mosaic-compile and agree bitwise with the XLA pass on-chip —
    ``digest_regime`` dispatches the Pallas twin on TPU backends, so
    this is the lowering proof for every on-chip digest round."""
    from go_crdt_playground_tpu.ops import digest as dg
    from go_crdt_playground_tpu.ops import pallas_digest

    row = jax.tree.map(lambda x: x[0], _delta_state(23))
    np.testing.assert_array_equal(
        np.asarray(dg.lane_fingerprints(row)),
        np.asarray(pallas_digest.pallas_lane_fingerprints(
            row, interpret=False)))
    np.testing.assert_array_equal(
        np.asarray(dg.state_group_digests(row, 64)),
        np.asarray(pallas_digest.pallas_state_group_digests(
            row, 64, interpret=False)))


def test_mesh2d_ingest_dispatch_compiles_and_matches():
    """The 2-D dp×mp striped super-batch program (ISSUE 15, DESIGN.md
    §24) must compile and agree bitwise with the sequential kernel on
    THIS backend's device set.  On a single-chip TPU host the mesh
    degenerates to (1, 1) — still the full shard_map + dissemination-
    join lowering path (scan + δ extraction in one program);
    multi-chip hosts exercise real dp striping and the ppermute join
    rounds.  The CPU suite covers dp×mp ≤ 8 under forced host
    devices; this smoke is the lowering proof capture_all.sh's mesh
    step rides on."""
    from go_crdt_playground_tpu.net.peer import Node
    from go_crdt_playground_tpu.parallel.meshtarget2d import \
        Mesh2DApplyTarget

    n_dev = jax.device_count()
    dp = 2 if n_dev >= 2 else 1
    mp = 2 if n_dev >= 4 else 1
    e, a, b = 512, 4, 8
    rng = np.random.default_rng(31)
    plain = Node(0, e, a)
    mesh = Mesh2DApplyTarget(0, e, a, mesh_shape=(dp, mp))
    for _ in range(3):
        add = rng.random((b, e)) < 0.05
        dl = rng.random((b, e)) < 0.02
        live = rng.random(b) < 0.9
        plain.ingest_batch(add, dl, live)
        mesh.ingest_batch(add, dl, live)
    sp, sm = plain.state_slice(), mesh.state_slice()
    for name in sp._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(sp, name)),
            np.asarray(getattr(sm, name)), err_msg=name)
