"""Digest-kernel laws (ops/digest.py + ops/pallas_digest.py).

The digest-sync protocol (net/digestsync.py, DESIGN.md §19) leans on
exactly these properties, so each is pinned:

* soundness — a group-digest mismatch PROVES a lane in the group
  differs (equal lanes always fingerprint equal, deterministically);
* padding stability — the ragged last group digests identically
  however the kernel pads the lane axis (XLA group-multiple padding
  vs Pallas 128-lane blocks), so two replicas always compare like
  with like;
* collision behavior — the documented 2^-32-per-group bound is
  probabilistic, but single-lane perturbations must never collide in
  any direct sweep (an avalanche sanity floor, not a proof);
* Pallas-vs-XLA bitwise identity across occupancies and shapes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_crdt_playground_tpu.models import awset_delta
from go_crdt_playground_tpu.ops import digest as dg
from go_crdt_playground_tpu.ops.pallas_digest import (
    pallas_lane_fingerprints, pallas_state_group_digests)

A = 4


def _slice(state, r=0):
    return jax.tree.map(lambda x: x[r], state)


def _random_state(e, seed, occupancy=0.5, deletions=0.3):
    """One seeded single-replica slice with live entries, deletion
    records, and re-adds (the delta_apply-reachable field shapes)."""
    rng = np.random.default_rng(seed)
    st = awset_delta.init(1, e, A)
    row = _slice(st)
    present = rng.random(e) < occupancy
    da = rng.integers(0, A, e).astype(np.uint32)
    dc = rng.integers(1, 50, e).astype(np.uint32)
    deleted = rng.random(e) < deletions
    dda = rng.integers(0, A, e).astype(np.uint32)
    ddc = rng.integers(1, 50, e).astype(np.uint32)
    vv = rng.integers(50, 100, A).astype(np.uint32)
    return row._replace(
        vv=jnp.asarray(vv),
        present=jnp.asarray(present),
        dot_actor=jnp.asarray(np.where(present, da, 0)),
        dot_counter=jnp.asarray(np.where(present, dc, 0)),
        deleted=jnp.asarray(deleted),
        del_dot_actor=jnp.asarray(np.where(deleted, dda, 0)),
        del_dot_counter=jnp.asarray(np.where(deleted, ddc, 0)),
        processed=jnp.asarray(vv))


def test_equal_lanes_equal_fingerprints_deterministic():
    s = _random_state(96, seed=1)
    f1 = np.asarray(dg.lane_fingerprints(s))
    f2 = np.asarray(dg.lane_fingerprints(s))
    np.testing.assert_array_equal(f1, f2)
    # a state rebuilt from the same arrays (fresh device buffers)
    # fingerprints identically: content, not identity
    s2 = jax.tree.map(lambda x: jnp.asarray(np.asarray(x)), s)
    np.testing.assert_array_equal(
        f1, np.asarray(dg.lane_fingerprints(s2)))


def test_mismatch_implies_lane_differs_soundness():
    """digest(a ⊔ b) vs digest(a): every group whose digest CHANGED
    must contain a lane that actually changed — the soundness pin the
    protocol ships lanes by."""
    from go_crdt_playground_tpu.ops import delta as delta_ops

    a = _random_state(256, seed=2)
    b = _random_state(256, seed=3)
    payload = delta_ops.delta_extract(b, a.vv)
    merged = delta_ops.delta_apply(a, payload, "v2")
    gs = 64
    d_a = np.asarray(dg.state_group_digests(a, gs))
    d_m = np.asarray(dg.state_group_digests(merged, gs))
    # the digest covers the CONVERGENT projection (live dots are
    # divergent by design and excluded — ops/digest.py docstring)
    changed_lane = np.zeros(256, bool)
    for name in ("present", "deleted", "del_dot_actor",
                 "del_dot_counter"):
        changed_lane |= (np.asarray(getattr(a, name))
                        != np.asarray(getattr(merged, name)))
    assert (d_a != d_m).any(), "the merge changed nothing — bad fixture"
    for g in np.nonzero(d_a != d_m)[0]:
        assert changed_lane[g * gs:(g + 1) * gs].any(), (
            f"group {g} digest mismatch without a differing lane")
    # and the contrapositive direction on this instance: groups with
    # NO differing lane digest equal (deterministic, not probabilistic)
    for g in np.nonzero(~(d_a != d_m))[0]:
        assert not changed_lane[g * gs:(g + 1) * gs].any()


def test_ragged_group_padding_stability():
    """E not a multiple of the group size: the ragged last group's
    digest depends only on the real lanes (zero-lane padding at true
    lane ids), so it is stable across every computation path."""
    e = 100  # 2 groups of 64: the second has 36 real + 28 pad lanes
    s = _random_state(e, seed=4)
    d1 = np.asarray(dg.state_group_digests(s, 64))
    assert d1.shape == (2,)
    d2 = np.asarray(dg.group_fold(dg.lane_fingerprints(s), 64))
    np.testing.assert_array_equal(d1, d2)
    d3 = np.asarray(pallas_state_group_digests(s, 64))
    np.testing.assert_array_equal(d1, d3)
    # mutating a PAD-ADJACENT real lane moves the last group's digest;
    # the first group never moves
    s2 = s._replace(present=s.present.at[99].set(~s.present[99]))
    d4 = np.asarray(dg.state_group_digests(s2, 64))
    assert d4[1] != d1[1] and d4[0] == d1[0]


def test_live_dot_divergence_is_digest_invisible():
    """The projection pin: two replicas differing ONLY in a present
    lane's live dot (the reference both-present overwrite leaves
    exactly this divergence after concurrent adds) digest EQUAL —
    the regime must go quiescent on observably-converged fleets
    instead of re-shipping dot-divergent lanes forever."""
    s = _random_state(128, seed=7)
    swapped = s._replace(
        dot_actor=jnp.where(s.present, (s.dot_actor + 1) % A,
                            s.dot_actor),
        dot_counter=jnp.where(s.present, s.dot_counter + 5,
                              s.dot_counter))
    np.testing.assert_array_equal(
        np.asarray(dg.state_group_digests(s, 64)),
        np.asarray(dg.state_group_digests(swapped, 64)))


def test_lane_id_folded_in():
    """Two lanes with IDENTICAL content fingerprint differently (lane
    id is folded in), so a content swap between lanes is visible and
    the group XOR fold cannot cancel equal-content lanes."""
    e = 8
    st = awset_delta.init(1, e, A)
    row = _slice(st)
    same = row._replace(
        present=jnp.ones(e, bool),
        dot_actor=jnp.full(e, 1, jnp.uint32),
        dot_counter=jnp.full(e, 7, jnp.uint32))
    fp = np.asarray(dg.lane_fingerprints(same))
    assert len(set(fp.tolist())) == e


def test_single_lane_perturbations_never_collide_in_sweep():
    """Avalanche floor under the documented 2^-32 bound: for one base
    state, every single-field single-lane perturbation produces a
    distinct group digest (2k+ trials — a weak mix would collide
    here long before the bound says it may)."""
    e = 64
    s = _random_state(e, seed=5)
    base = int(np.asarray(dg.state_group_digests(s, 64))[0])
    seen = {base}
    for lane in range(0, e, 2):
        for field, delta in (("del_dot_counter", 1),
                             ("del_dot_counter", 1000),
                             ("del_dot_counter", 3),
                             ("del_dot_actor", 1)):
            arr = getattr(s, field)
            mutated = s._replace(
                **{field: arr.at[lane].set(arr[lane] + delta)})
            d = int(np.asarray(dg.state_group_digests(mutated, 64))[0])
            assert d != base
            seen.add(d)
    # distinct perturbations are also pairwise distinct in this sweep
    assert len(seen) == 1 + (e // 2) * 4


@pytest.mark.parametrize("e", [48, 64, 200, 512])
def test_pallas_bitwise_pin_across_shapes(e):
    s = _random_state(e, seed=6 + e)
    np.testing.assert_array_equal(
        np.asarray(dg.lane_fingerprints(s)),
        np.asarray(pallas_lane_fingerprints(s)))
    np.testing.assert_array_equal(
        np.asarray(dg.state_group_digests(s, 64)),
        np.asarray(pallas_state_group_digests(s, 64)))


def test_pallas_bitwise_pin_across_occupancy_extremes():
    for occ, dels in ((0.0, 0.0), (1.0, 0.0), (0.0, 1.0), (1.0, 1.0)):
        s = _random_state(128, seed=9, occupancy=occ, deletions=dels)
        np.testing.assert_array_equal(
            np.asarray(dg.lane_fingerprints(s)),
            np.asarray(pallas_lane_fingerprints(s)))


def test_digest_diff_payload_extracts_only_mismatched_groups():
    """The on-device mismatching-lane extraction: lanes in digest-
    matched groups never appear; every shipped lane sits in a
    mismatched group; a self-comparison ships nothing."""
    e, gs = 256, 64
    a = _random_state(e, seed=10)
    b = a._replace(  # perturb exactly one lane in group 1 (the
        # projection the digest covers: membership + deletion log)
        present=a.present.at[70].set(~a.present[70]),
        deleted=a.deleted.at[70].set(True),
        del_dot_actor=a.del_dot_actor.at[70].set(2),
        del_dot_counter=a.del_dot_counter.at[70].set(99))
    d_a = dg.state_group_digests(a, gs)
    d_b = dg.state_group_digests(b, gs)
    p = dg.digest_diff_payload(a, d_a, d_b, gs)
    ch = np.nonzero(np.asarray(p.changed))[0]
    dl = np.nonzero(np.asarray(p.deleted))[0]
    assert len(ch) or len(dl)
    for lane in np.concatenate([ch, dl]):
        assert 64 <= lane < 128, f"lane {lane} outside mismatched group"
    # self-comparison: zero lanes (the quiescent round's zero-state-
    # lanes guarantee is this property plus the wire layer)
    p0 = dg.digest_diff_payload(a, d_a, d_a, gs)
    assert not np.asarray(p0.changed).any()
    assert not np.asarray(p0.deleted).any()
    # the full vv rides the payload (digest-matched withholding is
    # clock-safe — ops/digest.py docstring)
    np.testing.assert_array_equal(np.asarray(p.src_vv),
                                  np.asarray(a.vv))


def test_digest_regime_dispatch():
    fn = dg.digest_regime(128)
    s = _random_state(128, seed=11)
    expected = (pallas_state_group_digests if jax.default_backend()
                == "tpu" else dg.state_group_digests)
    np.testing.assert_array_equal(np.asarray(fn(s, 64)),
                                  np.asarray(expected(s, 64)))
