"""Parallel-layer tests on a virtual 8-device CPU mesh: sharding
transparency (sharded == unsharded bitwise), convergence of every schedule,
fault injection, the explicit shard_map ring, and the collective reductions.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from go_crdt_playground_tpu.models import awset, awset_delta
from go_crdt_playground_tpu.ops import delta as delta_ops
from go_crdt_playground_tpu.parallel import collectives, gossip, mesh as mesh_mod


def _random_state(rng, R=16, E=32, A=16, delta=False):
    """Independent replica histories via the jitted local ops."""
    st = (awset_delta if delta else awset).init(R, E, A)
    for _ in range(4 * R):
        r = rng.randrange(R)
        e = rng.randrange(E)
        if rng.random() < 0.75:
            st = (awset_delta if delta else awset).add_element(
                st, np.uint32(r), np.uint32(e))
        elif delta:
            sel = np.zeros(E, bool)
            sel[e] = True
            st = awset_delta.del_elements(st, np.uint32(r), np.asarray(sel))
        else:
            st = awset.del_element(st, np.uint32(r), np.uint32(e))
    return st


def _assert_states_equal(a, b, context=""):
    for name in a._fields:
        assert np.array_equal(np.asarray(getattr(a, name)),
                              np.asarray(getattr(b, name))), (context, name)


def test_eight_virtual_devices_present():
    assert len(jax.devices()) == 8


def test_sharded_gossip_bitwise_equals_unsharded():
    """The same gossip round must produce identical bytes whether the
    replica/element axes are sharded over the mesh or on one device —
    sharding is a layout choice, never a semantics choice."""
    import random
    rng = random.Random(5)
    state = _random_state(rng)
    R = state.vv.shape[0]
    perm = gossip.ring_perm(R, 3)
    plain = gossip.gossip_round_jit(state, perm)
    m = mesh_mod.make_mesh((4, 2))
    sharded_in = mesh_mod.shard_state(state, m)
    sharded = gossip.gossip_round_jit(sharded_in, perm)
    _assert_states_equal(plain, sharded, "ring offset 3")
    # butterfly stage too
    perm2 = gossip.butterfly_perm(R, 2)
    _assert_states_equal(
        gossip.gossip_round_jit(state, perm2),
        gossip.gossip_round_jit(sharded_in, perm2),
        "butterfly stage 2",
    )


def test_all_pairs_converges_to_union_log2_rounds():
    import random
    rng = random.Random(7)
    state = _random_state(rng, R=16, E=32, A=16)
    out = gossip.all_pairs_converge(state)
    present = np.asarray(out.present)
    vv = np.asarray(out.vv)
    assert bool(collectives.converged(out.present, out.vv))
    # all replicas agree
    assert (present == present[0]).all()
    assert (vv == vv[0]).all()
    # VV is the global join
    assert np.array_equal(vv[0], np.asarray(
        collectives.global_vv_join(state.vv)))


def test_rounds_to_convergence_dissemination_bound():
    import random
    rng = random.Random(9)
    state = _random_state(rng, R=16)
    rounds, out = gossip.rounds_to_convergence(state)
    assert bool(collectives.converged(out.present, out.vv))
    assert rounds <= 4 + 1, rounds  # ceil(log2 16) = 4 (+1 slack)


@pytest.mark.parametrize("drop_rate", [0.3, 0.6])
def test_convergence_under_message_drops(drop_rate):
    """Masked merges (lost exchanges) must still converge — the
    self-healing property the reference documents (awset.go:28-35) turned
    into a fault-injection test (SURVEY §5.3)."""
    import random
    rng = random.Random(11)
    state = _random_state(rng, R=16)
    rounds, out = gossip.rounds_to_convergence(
        state, key=jax.random.PRNGKey(0), drop_rate=drop_rate,
        schedule="random", max_rounds=500)
    assert bool(collectives.converged(out.present, out.vv)), drop_rate
    assert rounds < 500


def test_delta_gossip_converges_and_gc_empties_log():
    import random
    rng = random.Random(13)
    state = _random_state(rng, R=8, E=16, A=8, delta=True)
    R = 8
    for off in gossip.dissemination_offsets(R) * 2:
        state = gossip.delta_gossip_round_jit(
            state, gossip.ring_perm(R, off))
    assert bool(collectives.converged(state.present, state.vv))
    frontier = delta_ops.gc_frontier(state.processed)
    cleaned = delta_ops.gc_apply(state, frontier)
    assert not np.asarray(cleaned.deleted).any()


def test_delta_gossip_sharded_equals_unsharded():
    import random
    rng = random.Random(17)
    state = _random_state(rng, R=8, E=16, A=8, delta=True)
    perm = gossip.ring_perm(8, 1)
    plain = gossip.delta_gossip_round_jit(state, perm)
    m = mesh_mod.make_mesh((8, 1))
    sharded = gossip.delta_gossip_round_jit(
        mesh_mod.shard_state(state, m), perm)
    _assert_states_equal(plain, sharded)


def test_pipelined_delta_gossip_converges_to_same_fixed_point():
    """The double-buffered PP schedule (one round of payload staleness)
    must reach the same (membership, VV) fixed point as the unpipelined
    δ gossip — staleness only delays shipment, never changes the join."""
    import random
    rng = random.Random(37)
    R = 16
    state = _random_state(rng, R=R, E=32, A=16, delta=True)
    offsets = gossip.dissemination_offsets(R)
    # pipeline depth 2 => cycle the dissemination schedule enough times
    # to cover the lag (2x + slack)
    perms = jnp.stack([gossip.ring_perm(R, o) for o in offsets] * 3)
    piped = gossip.pipelined_delta_gossip(state, perms)
    assert bool(collectives.converged(piped.present, piped.vv))
    ref = gossip.all_pairs_converge(state, delta=True,
                                    delta_semantics="v2")
    assert bool(collectives.converged(ref.present, ref.vv))
    assert np.array_equal(np.asarray(piped.present), np.asarray(ref.present))
    assert np.array_equal(np.asarray(piped.vv), np.asarray(ref.vv))


def test_pipelined_round_lag_is_exactly_one():
    """Data added before round 0 reaches the ring neighbor at round 1
    (payload for round 0 is extracted fresh), but data present only in
    the staged buffer propagates with the documented one-round lag."""
    R, E, A = 4, 8, 4
    state = awset_delta.init(R, E, A)
    state = awset_delta.add_element(state, np.uint32(0), np.uint32(3))
    perms = jnp.stack([gossip.ring_perm(R, 1)])  # replica r absorbs r+1
    one = gossip.pipelined_delta_gossip(state, perms)
    # replica 3 absorbs replica 0's fresh payload in round 0
    assert bool(one.present[3, 3])
    assert not bool(one.present[2, 3])


def test_ring_shardmap_matches_equivalent_gather_round():
    """The explicit ppermute ring (device i's block -> device i+1) is the
    gather round with offset -shard_size; both paths must agree bitwise."""
    import random
    rng = random.Random(19)
    R = 16
    state = _random_state(rng, R=R)
    m = mesh_mod.make_mesh((8, 1))
    sharded = mesh_mod.shard_state(state, m)
    ring = gossip.ring_round_shardmap(sharded, m)
    shard_size = R // 8
    perm = (jnp.arange(R, dtype=jnp.uint32) - shard_size) % R
    expected = gossip.gossip_round_jit(state, perm)
    _assert_states_equal(ring, expected)


def test_ring_shardmap_pallas_matches_xla():
    """The per-shard fused Pallas ring (the TPU-mesh fast path,
    VERDICT r1 #3) must agree bitwise with the XLA shard_map ring AND
    the unsharded gather round — on the CPU test mesh the kernel runs
    in interpret mode, on real TPU it is the Mosaic program."""
    import random
    rng = random.Random(23)
    R = 16
    for shape in ((8, 1), (4, 2)):
        state = _random_state(rng, R=R, E=32)
        m = mesh_mod.make_mesh(shape)
        sharded = mesh_mod.shard_state(state, m)
        fused = gossip.ring_round_shardmap(sharded, m, kernel="pallas")
        plain = gossip.ring_round_shardmap(sharded, m, kernel="xla")
        _assert_states_equal(fused, plain, f"mesh {shape}")
        shard_size = R // shape[0]
        perm = (jnp.arange(R, dtype=jnp.uint32) - shard_size) % R
        _assert_states_equal(fused, gossip.gossip_round_jit(state, perm),
                             f"mesh {shape} vs gather")


def test_ep_ring_matches_replicated_actor_ring():
    """EP layout (vv's actor axis sharded over the mesh element dim,
    SURVEY §2.3 EP row) must be invisible in the results: the EP ring
    round agrees bitwise with the replicated-actor ring round on the
    same mesh, and with the equivalent gather round."""
    import random
    rng = random.Random(29)
    R, A = 16, 16
    state = _random_state(rng, R=R, E=32, A=A)
    for shape in ((4, 2), (2, 4)):
        m = mesh_mod.make_mesh(shape)
        ep = gossip.ep_ring_round_shardmap(
            mesh_mod.shard_state(state, m, shard_actors=True), m)
        plain = gossip.ring_round_shardmap(
            mesh_mod.shard_state(state, m), m)
        _assert_states_equal(ep, plain, f"mesh {shape}")
        shard_size = R // shape[0]
        perm = (jnp.arange(R, dtype=jnp.uint32) - shard_size) % R
        _assert_states_equal(ep, gossip.gossip_round_jit(state, perm),
                             f"mesh {shape} vs gather")


def test_ep_ring_rejects_indivisible_actor_axis():
    state = awset.init(16, 32, 12, actors=np.arange(16) % 12)
    m = mesh_mod.make_mesh((1, 8))   # A=12 not divisible by 8
    with pytest.raises(ValueError):
        gossip.ep_ring_round_shardmap(state, m)
    with pytest.raises(ValueError):
        mesh_mod.shard_state(state, m, shard_actors=True)


def test_ormap_gossip_round_matches_lattice_join():
    """The fast OR-Map round (AWSet kernel for membership + elementwise
    LWW for cells) is bitwise the generic lattice-join round."""
    import random
    from go_crdt_playground_tpu.ops import lattices as L

    rng = random.Random(73)
    R, E = 8, 16
    st = L.ormap_init(R, E, R)
    ts = 0
    for _ in range(60):
        r, e = rng.randrange(R), rng.randrange(E)
        if rng.random() < 0.7:
            ts += 1
            st = L.ormap_put(st, np.uint32(r), np.uint32(e),
                             np.uint32(rng.randrange(1, 99)), np.uint32(ts))
        else:
            st = L.ormap_delete(st, np.uint32(r), np.uint32(e))
    for off in (1, 3):
        perm = gossip.ring_perm(R, off)
        want = L.gossip_round(L.ormap_join, st, perm)
        for kernel in ("xla", "pallas"):
            got = gossip.ormap_gossip_round(st, perm, kernel=kernel)
            _assert_states_equal(want, got, f"off {off} kernel {kernel}")
        st = want


def test_config_factories():
    from go_crdt_playground_tpu.config import REFERENCE_CONFIG, Config

    st = REFERENCE_CONFIG.init_awset()
    assert st.present.shape == (3, 16) and st.vv.shape == (3, 3)
    d = REFERENCE_CONFIG.element_dict()
    assert d.capacity == 16
    cfg = Config(num_replicas=8, num_elements=32, num_actors=8,
                 mesh_shape=(4, 2))
    ds = cfg.init_awset_delta()
    assert ds.deleted.shape == (8, 32)
    m = cfg.make_mesh()
    assert dict(m.shape) == {"replica": 4, "element": 2}


def test_gossip_determinism():
    import random
    rng = random.Random(23)
    state = _random_state(rng)
    perm = gossip.ring_perm(16, 5)
    a = gossip.gossip_round_jit(state, perm)
    b = gossip.gossip_round_jit(state, perm)
    _assert_states_equal(a, b)


def test_butterfly_stage_guard():
    with pytest.raises(ValueError):
        gossip.butterfly_perm(8, 3)   # 1<<3 == 8: JAX would clamp silently
    with pytest.raises(ValueError):
        gossip.butterfly_perm(12, 1)  # not a power of two


def test_rounds_to_convergence_raises_on_budget_exhaustion():
    import random
    rng = random.Random(3)
    state = _random_state(rng, R=16)
    with pytest.raises(RuntimeError):
        gossip.rounds_to_convergence(
            state, key=jax.random.PRNGKey(0), drop_rate=0.99,
            schedule="random", max_rounds=3)


def test_membership_hash_properties():
    present = jnp.zeros((3, 16), bool)
    h0 = np.asarray(collectives.membership_hash(present))
    assert (h0 == 0).all()
    p1 = present.at[0, 3].set(True).at[0, 7].set(True)
    p2 = present.at[1, 7].set(True).at[1, 3].set(True)  # order-free
    h = np.asarray(collectives.membership_hash(p1 | p2))
    assert h[0] == h[1] != 0
    # digest includes the VV
    vv = jnp.zeros((3, 4), jnp.uint32)
    d1 = np.asarray(collectives.state_digest(p1 | p2, vv))
    d2 = np.asarray(collectives.state_digest(p1 | p2, vv.at[0, 0].set(1)))
    assert d1[0] != d2[0]


@pytest.mark.parametrize("check_every", [1, 4, 32])
def test_rounds_to_convergence_chunked_exact(check_every):
    """The chunked convergence loop returns the SAME minimal round count
    for any chunk size (bisect replays from the chunk start with
    index-derived randomness), including under drops."""
    import random
    rng = random.Random(21)
    state = _random_state(rng, R=16)
    want_rounds, want_out = gossip.rounds_to_convergence(
        state, key=jax.random.PRNGKey(5), drop_rate=0.4,
        schedule="random", max_rounds=300, check_every=1)
    got_rounds, got_out = gossip.rounds_to_convergence(
        state, key=jax.random.PRNGKey(5), drop_rate=0.4,
        schedule="random", max_rounds=300, check_every=check_every)
    assert got_rounds == want_rounds
    for a, b in zip(jax.tree.leaves(want_out), jax.tree.leaves(got_out)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_ormap_ring_round_matches_perm_round():
    """Offset-form OR-Map ring round == perm-form round, bitwise, on
    both kernel paths (pallas runs in interpret mode on CPU) and with
    traced offsets through a scanned schedule."""
    import random
    from go_crdt_playground_tpu.ops import lattices as L

    rng = random.Random(31)
    from go_crdt_playground_tpu.ops import pallas_merge

    R_, E_ = 2 * pallas_merge._BLOCK_R, 8  # ring-kernel-eligible R
    st = L.ormap_init(R_, E_, R_)
    ts = 0
    for _ in range(60):
        r, e = rng.randrange(R_), rng.randrange(E_)
        if rng.random() < 0.6:
            ts += 1
            st = L.ormap_put(st, np.uint32(r), np.uint32(e),
                             np.uint32(rng.randrange(1, 99)),
                             np.uint32(ts))
        else:
            st = L.ormap_delete(st, np.uint32(r), np.uint32(e))
    st0 = st
    for off in (1, 5, 15):
        want = gossip.ormap_gossip_round(st, gossip.ring_perm(R_, off),
                                         kernel="xla")
        for kernel in ("xla", "pallas"):
            got = gossip.ormap_ring_gossip_round(st, off, kernel=kernel)
            for a, b in zip(jax.tree.leaves(want), jax.tree.leaves(got)):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                              err_msg=f"{off}/{kernel}")
        st = want

    # traced offsets through a scanned schedule reuse one program
    offsets = jnp.asarray([1, 5, 15], jnp.uint32)

    @jax.jit
    def run(s):
        def body(c, off):
            return gossip.ormap_ring_gossip_round(c, off), None
        return jax.lax.scan(body, s, offsets)[0]

    got = run(st0)
    for a, b in zip(jax.tree.leaves(st), jax.tree.leaves(got)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_packed_block_ring_shardmap_bitwise_and_converges():
    """The sharded bitpacked δ ring (gossip.packed_block_ring_round_shardmap):

    * block-aligned offsets must equal the single-device packed ring
      round bitwise (same global pairing, explicit ppermute + stacked
      kernel is pure layout);
    * intra offsets must equal the per-block packed round bitwise
      (documented per-block wraparound pairing);
    * the composed dissemination schedule (intra doublings then block
      doublings) must converge the fleet.
    """
    import random

    from go_crdt_playground_tpu.models import packed as packed_mod
    from go_crdt_playground_tpu.ops import pallas_delta
    from tests.test_pallas_delta import _scenario_state

    n = 8
    blk = 64
    R, E, A = n * blk, 96, 8
    rng = random.Random(11)
    state = _scenario_state(rng, R, E, A)
    packed = packed_mod.pack_awset_delta(state)
    m = mesh_mod.make_mesh((n, 1))
    sharded = mesh_mod.shard_state(packed, m)

    # block-aligned: bitwise vs the global packed ring round
    got = gossip.packed_block_ring_round_shardmap(sharded, m, blk)
    want = pallas_delta.pallas_delta_ring_round_packed(packed, blk)
    for name in want._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(got, name)),
            np.asarray(getattr(want, name)), err_msg=f"aligned/{name}")

    # intra: bitwise vs the packed round applied per block
    off = 3
    got = gossip.packed_block_ring_round_shardmap(sharded, m, off)
    for b in range(n):
        sl = slice(b * blk, (b + 1) * blk)
        block = jax.tree.map(lambda x: x[sl], packed)
        # per-block reference via the stacked form on one device (blk=64
        # alone is below ring_supported, which is exactly why the
        # shard_map path stacks)
        stacked = jax.tree.map(
            lambda x: jnp.concatenate([x, x], axis=0), block)
        want_b = jax.tree.map(
            lambda x: x[:blk],
            pallas_delta.pallas_delta_ring_round_packed(stacked, blk + off))
        for name in want_b._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(got, name))[sl],
                np.asarray(getattr(want_b, name)),
                err_msg=f"intra/block{b}/{name}")

    # composed dissemination: intra doublings, then block doublings
    st = sharded
    o = 1
    while o < blk:
        st = gossip.packed_block_ring_round_shardmap(st, m, o)
        o *= 2
    while o < R:
        st = gossip.packed_block_ring_round_shardmap(st, m, o)
        o *= 2
    assert bool(collectives.converged_packed(st.present_bits, st.vv))
    # and it must agree with the bool-layout convergence digest
    unpacked = packed_mod.unpack_awset_delta(
        jax.tree.map(np.asarray, st), E)
    assert bool(collectives.converged(unpacked.present, unpacked.vv))


def test_packed_block_ring_shardmap_rejects_untileable_block():
    """An R/mesh combo whose per-device block stacks below the packed
    ring kernel's tiling must fail at the API boundary with a clear
    error, not inside kernel layout asserts (ADVICE r4)."""
    from go_crdt_playground_tpu.models import packed as packed_mod

    n = 8
    R, E, A = n * 8, 96, 64  # blk=8 -> stacked block 16 rows: untileable
    state = awset_delta.init(R, E, A)
    packed = packed_mod.pack_awset_delta(state)
    m = mesh_mod.make_mesh((n, 1))
    sharded = mesh_mod.shard_state(packed, m)
    with pytest.raises(ValueError, match="stacks to a 16-row"):
        gossip.packed_block_ring_round_shardmap(sharded, m, 8)


def test_butterfly_shardmap_bitwise_and_converges():
    """The mesh-native butterfly stage (gossip.butterfly_round_shardmap,
    VERDICT r4 weakness #4): every stage — block-local and device-swap,
    XLA and per-shard fused kernels — must equal the unsharded butterfly
    round bitwise, and the full hypercube schedule must converge."""
    import random
    rng = random.Random(41)
    R = 16
    state = _random_state(rng, R=R, E=32, A=16)
    for shape in ((8, 1), (4, 2)):
        m = mesh_mod.make_mesh(shape)
        sharded = mesh_mod.shard_state(state, m)
        for stage in range(4):  # blk=2: stage 0 local; 1..3 device swaps
            want = gossip.gossip_round_jit(
                state, gossip.butterfly_perm(R, stage))
            for kernel in ("xla", "pallas"):
                got = gossip.butterfly_round_shardmap(
                    sharded, m, stage, kernel=kernel)
                _assert_states_equal(
                    got, want, f"mesh {shape} stage {stage} {kernel}")
    # full hypercube schedule = all-pairs convergence
    m = mesh_mod.make_mesh((4, 2))
    st = mesh_mod.shard_state(state, m)
    for stage in range(4):
        st = gossip.butterfly_round_shardmap(st, m, stage)
    assert bool(collectives.converged(st.present, st.vv))


def test_butterfly_shardmap_validation():
    import random
    rng = random.Random(43)
    m = mesh_mod.make_mesh((8, 1))
    with pytest.raises(ValueError, match="power-of-two replica"):
        gossip.butterfly_round_shardmap(
            mesh_mod.shard_state(_random_state(rng, R=24, A=24), m), m, 1)
    st = mesh_mod.shard_state(_random_state(rng, R=16), m)
    with pytest.raises(ValueError, match="out of range"):
        gossip.butterfly_round_shardmap(st, m, 4)


def test_multi_device_tpu_slow_path_warns(monkeypatch):
    """A general-perm gossip round on a multi-device TPU process drops
    to the ~40x XLA HasDot path; that must be LOUD (VERDICT r4 weakness
    #4), while kernel='xla' acknowledges it silently."""
    import warnings as warnings_mod

    import random
    rng = random.Random(47)
    state = _random_state(rng, R=8, E=16, A=8)
    perm = gossip.butterfly_perm(8, 1)
    monkeypatch.setattr(gossip.jax, "default_backend", lambda: "tpu")
    with pytest.warns(UserWarning, match="40x"):
        gossip.gossip_round(state, perm)
    # explicit kernel choice is an acknowledgement — no warning
    with warnings_mod.catch_warnings():
        warnings_mod.simplefilter("error")
        gossip.gossip_round(state, perm, kernel="xla")


def test_butterfly_schedule_converges_in_exactly_log2_rounds():
    """The butterfly schedule's m distinct XOR stages are hypercube
    dissemination: a divergent power-of-two fleet converges in exactly
    ceil(log2 R) rounds — the tight bound, not just <= with slack."""
    import random
    rng = random.Random(53)
    state = _random_state(rng, R=16, E=32, A=16)
    rounds, out = gossip.rounds_to_convergence(state, schedule="butterfly")
    assert bool(collectives.converged(out.present, out.vv))
    assert rounds == 4
    with pytest.raises(ValueError, match="power-of-two"):
        gossip.rounds_to_convergence(
            _random_state(rng, R=12, A=12), schedule="butterfly")


def test_dotword_block_ring_shardmap_bitwise_and_converges():
    """packed_block_ring_round_shardmap on the DOT-WORD δ layout
    (uint32 dot words crossing ICI — ~1.5x less ring-cut traffic than
    the bitpacked layout): block-aligned offsets must equal the
    single-device dot-word ring bitwise; the composed dissemination
    schedule must converge."""
    import random

    from go_crdt_playground_tpu.models import packed as packed_mod
    from go_crdt_playground_tpu.ops import pallas_delta
    from tests.test_pallas_delta import _scenario_state

    n, blk = 8, 64
    R, E, A = n * blk, 96, 8
    rng = random.Random(83)
    state = _scenario_state(rng, R, E, A)
    packed = packed_mod.pack_awset_delta_dots(state)
    m = mesh_mod.make_mesh((n, 1))
    sharded = mesh_mod.shard_state(packed, m)

    got = gossip.packed_block_ring_round_shardmap(sharded, m, blk)
    want = pallas_delta.pallas_delta_ring_round_dotpacked(packed, blk)
    for name in want._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(got, name)),
            np.asarray(getattr(want, name)), err_msg=f"aligned/{name}")

    st, o = sharded, 1
    while o < R:
        st = gossip.packed_block_ring_round_shardmap(st, m, o)
        o *= 2
    out = packed_mod.unpack_awset_delta_dots(st, E)
    assert bool(collectives.converged(out.present, out.vv))


def test_fullstate_packed_block_ring_shardmap_bitwise():
    """The sharded block ring also serves the FULL-STATE packed layouts
    (bitpacked and dot-word AWSetState): block-aligned offsets bitwise-
    equal the single-device kernels."""
    from go_crdt_playground_tpu.models import packed as packed_mod
    from go_crdt_playground_tpu.ops import pallas_merge
    from tests.test_packed import rand_state

    n, blk = 8, 64
    R, E, A = n * blk, 96, 8
    rng = np.random.default_rng(87)
    state = rand_state(rng, R, E, A)
    m = mesh_mod.make_mesh((n, 1))
    for pack, ring in (
            (packed_mod.pack_awset,
             pallas_merge.pallas_ring_round_rows_packed),
            (packed_mod.pack_awset_dots,
             pallas_merge.pallas_ring_round_rows_dotpacked)):
        p = pack(state)
        sharded = mesh_mod.shard_state(p, m)
        got = gossip.packed_block_ring_round_shardmap(sharded, m, blk)
        want = ring(p, blk)
        for name in want._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(got, name)),
                np.asarray(getattr(want, name)),
                err_msg=f"{pack.__name__}/{name}")
